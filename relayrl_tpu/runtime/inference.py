"""Disaggregated batched-inference serving plane (ROADMAP item 2).

Every actor tier so far holds its own policy replica and swaps full
params — the right shape for rollout throughput, the wrong one for the
"millions of users" serving scenario, where the fleet is wide, stateless,
and latency-bound. TorchBeast (arXiv:1910.03552) showed the answer is a
**dynamic-batching inference server**: accept observation requests, close
a batch on a size-or-deadline trigger, run ONE batched policy step, and
stream the actions back; Podracer's Sebulba split (arXiv:2104.06272)
colocates that service with the learner devices so actors become
near-stateless thin clients.

This module is both halves:

* :class:`InferenceService` — the latency-bounded dynamic-batching queue
  plus ONE ``jit(vmap)`` policy dispatch per closed batch
  (``make_batched_step`` — the exact composition every other actor tier
  jits, so a served action is bit-identical to a locally computed one for
  the same key). Batch shapes are bucketed to a small compiled set
  (``pick_bucket`` over ``serving.buckets``) and padded rows are sliced
  off before replies, so arbitrary occupancies never retrace. The service
  always serves the latest fenced params version: params are read ONCE
  per batch under the shared swap gate (``apply_bundle_swap`` — the same
  attribute contract PolicyActor/VectorActorHost/AnakinActorHost share),
  so a batch is single-model-version by construction even against a
  racing swapper. Overload (queue at ``serving.queue_limit``) answers
  with a typed ``NACK_OVERLOADED`` + retry-after instead of queueing
  unboundedly — a flood of inference clients cannot starve the learner's
  ingest plane.

* :class:`RemoteActorClient` — the thin-client actor
  (``actor.host_mode: "remote"``): no params, no model subscription, no
  swap gate; just a request/response loop carrying its PRNG key (the
  service splits it in-dispatch and returns the successor, so the
  client's action stream IS a PolicyActor's for the same seed). The
  trajectory plane — Trajectory assembly, spool/seq tagging, transport
  envelopes — is byte-identical to a local actor's, so the learner's
  ingest funnel cannot tell the tiers apart.

Colocated mode: the TrainingServer feeds :meth:`install_params` from its
publish path in-process — the service sees every published version with
ZERO wire hops. Standalone mode (dedicated serving devices):
:class:`StandaloneInferenceHost` subscribes over any agent transport like
an actor would and hosts the same service.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from relayrl_tpu.data.batching import pick_bucket
from relayrl_tpu.transport.base import (
    NACK_OK,
    NACK_OVERLOADED,
    NACK_UNAVAILABLE,
)
from relayrl_tpu.transport.serving import (
    pack_action_reply,
    pack_infer_nack,
    pack_infer_request,
    pack_infer_wave,
    pack_reply_wave,
    unpack_infer_any,
    unpack_infer_request,
)
from relayrl_tpu.runtime.policy_actor import push_window
from relayrl_tpu.types.action import ActionRecord
from relayrl_tpu.types.model_bundle import ModelBundle, exploration_kwargs
from relayrl_tpu.types.trajectory import Trajectory

CLOSE_SIZE = "size"
CLOSE_DEADLINE = "deadline"


class InferRequest:
    """One queued observation request (decoded, transport-agnostic).
    ``sid``/``rst``/``stp``/``win`` are the serving-v2 session fields
    (None/False/0 on the v1 stateless wire); ``window_row``/``window_t``
    are filled by the batch worker once the session table resolves the
    request into a dispatchable window row."""

    __slots__ = ("agent_id", "req_id", "key", "obs", "mask", "reply",
                 "t_enqueue", "trace", "t_enqueue_ns", "wave",
                 "sid", "rst", "stp", "win", "window_row", "window_t")

    def __init__(self, agent_id, req_id, key, obs, mask, reply,
                 sid=None, rst=False, stp=0, win=None, wave=False):
        self.agent_id = agent_id
        self.req_id = req_id
        self.key = key
        self.obs = obs
        self.mask = mask
        self.reply = reply
        # Wave-arrived requests share one reply pipe; served actions for
        # batchmates from the same wave leave as one coalesced frame.
        self.wave = wave
        self.sid = sid
        self.rst = rst
        self.stp = stp
        self.win = win
        self.window_row = None
        self.window_t = 0
        self.t_enqueue = time.monotonic()
        # Distributed tracing (telemetry/trace.py): a sampled request
        # draws a serve-plane trace id at submit; its queue/dispatch
        # hops record at batch execution.
        self.trace = None
        self.t_enqueue_ns = 0


class _Session:
    """Server-side per-session serving state for sequence policies: the
    rolling observation window a transformer serves from, so the client
    never ships context with a step. Reconstructible-from-client by
    contract (the resync payload), so losing one — LRU eviction, TTL
    expiry, replica death — costs a resync round-trip, never an episode.
    ``episode_step`` is the push-idempotency cursor (see
    ``pack_infer_request``'s ``stp``)."""

    __slots__ = ("window", "length", "episode_step", "last_used")

    def __init__(self, ctx: int, obs_dim: int, now: float):
        self.window = np.zeros((ctx, obs_dim), np.float32)
        self.length = 0
        self.episode_step = 0
        self.last_used = now


def default_buckets(max_batch: int) -> list[int]:
    """Powers of two up to ``max_batch`` (inclusive, deduped): at most
    ~log2(max_batch) compiled dispatch shapes serve every occupancy."""
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(int(max_batch))
    return sorted(set(out))


class InferenceService:
    """Latency-bounded dynamic-batching policy server.

    Requests accumulate until ``max_batch`` arrivals (close reason
    ``size``) or ``batch_timeout_ms`` after the FIRST queued request of
    the batch (close reason ``deadline``), whichever fires first — the
    TorchBeast batching-server contract. ``queue_limit`` bounds waiting
    requests; beyond it submissions nack ``NACK_OVERLOADED`` with
    ``retry_after_s`` so clients back off instead of piling on.

    Swap surface: the service exposes the shared actor-host attribute
    contract (``version``/``arch``/``params``/``_explore_kwargs``/
    ``_lock``/``_wire_decoder``) so :func:`apply_bundle_swap` /
    :func:`apply_wire_swap` gate installs exactly as on every other
    actor tier — one params read per batch under ``_lock`` makes a batch
    single-version by construction.
    """

    def __init__(
        self,
        bundle: ModelBundle,
        max_batch: int = 16,
        batch_timeout_ms: float = 5.0,
        buckets=None,
        queue_limit: int = 1024,
        retry_after_s: float = 0.05,
        stale_after_s: float = 5.0,
        max_sessions: int = 4096,
        session_ttl_s: float = 600.0,
        validate: bool = True,
    ):
        import jax

        from relayrl_tpu.models import build_policy, validate_policy

        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._lock = threading.Lock()
        self.arch = dict(bundle.arch)
        self.policy = build_policy(self.arch)
        if validate:
            validate_policy(self.policy, bundle.params)
        self.params = bundle.params
        self.version = bundle.version
        self._explore_kwargs = exploration_kwargs(self.arch)
        self._wire_decoder = None
        from relayrl_tpu.runtime.policy_actor import (
            make_batched_step,
            make_batched_window_step,
            resolve_actor_context,
        )

        self._batched_fn = make_batched_step(self.policy)
        # Sequence policies (serving v2): the per-client rolling window
        # lives HERE, in the session table, keyed by the client-supplied
        # session id — the TorchBeast "server owns recurrent state"
        # shape. The dispatch is the same make_batched_window_step
        # composition every local tier jits, so a served sequence action
        # is bit-identical to a local windowed PolicyActor's for the
        # same key.
        self._window_fn = None
        self.ctx = 0
        if self.policy.step_window is not None:
            self.ctx = resolve_actor_context(self.arch)
            self._window_fn = make_batched_window_step(self.policy)
        from collections import OrderedDict

        self._sessions: "OrderedDict[str, _Session]" = OrderedDict()
        self.max_sessions = max(1, int(max_sessions))
        self.session_ttl_s = max(0.0, float(session_ttl_s))
        self._jax = jax

        self.max_batch = int(max_batch)
        self.batch_timeout_s = max(0.0, float(batch_timeout_ms)) / 1000.0
        self.buckets = sorted(set(
            int(b) for b in (buckets or default_buckets(self.max_batch))))
        if self.buckets[-1] < self.max_batch:
            # The largest bucket must cover a size-closed full batch, or
            # pick_bucket would clamp DOWN and the pad computation go
            # negative — every full batch would then fail forever. (The
            # ConfigLoader applies the same clamp; direct constructions
            # get it here.)
            self.buckets.append(self.max_batch)
        self.queue_limit = max(1, int(queue_limit))
        self.retry_after_s = max(0.0, float(retry_after_s))
        # Ghost-work guard: a request older than this has been abandoned
        # by its client (whose per-attempt timeout elapsed and whose
        # retry is already queued behind it) — dispatching it anyway
        # would double-serve every retry round and amplify exactly the
        # backlog that made it stale. Such entries are answered with a
        # retryable nack at batch-gather time instead. 0 disables.
        self.stale_after_s = max(0.0, float(stale_after_s))

        self._queue: deque[InferRequest] = deque()
        self._cond = threading.Condition()
        self._worker: threading.Thread | None = None
        self._stop = threading.Event()
        self._zmq_plane = None
        self._zmq_addr = None

        from relayrl_tpu import telemetry

        reg = telemetry.get_registry()
        self._m_requests = reg.counter(
            "relayrl_serving_requests_total",
            "observation requests accepted into the batching queue")
        self._m_rejected = reg.counter(
            "relayrl_serving_rejected_total",
            "requests nacked NACK_OVERLOADED at the queue limit")
        self._m_errors = reg.counter(
            "relayrl_serving_request_errors_total",
            "malformed/unservable requests answered with an error reply")
        self._m_batches = {
            reason: reg.counter(
                "relayrl_serving_batches_total",
                "closed inference batches by close trigger",
                {"reason": reason})
            for reason in (CLOSE_SIZE, CLOSE_DEADLINE)}
        self._m_stale = reg.counter(
            "relayrl_serving_stale_dropped_total",
            "queued requests nacked unserved because they outlived "
            "serving.stale_after_s (their client already timed out and "
            "retried — dispatching them would double-serve ghost work)")
        self._m_occupancy = reg.histogram(
            "relayrl_serving_batch_occupancy",
            "requests per closed batch (occupancy > 1 = batching works)",
            # jaxlint: disable=MET03 - dimensionless request count, not a dimensioned unit
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
        self._m_dispatch_s = reg.histogram(
            "relayrl_serving_dispatch_seconds",
            "one batched policy dispatch (device compute + reply encode)")
        from relayrl_tpu.telemetry.core import LATENCY_BUCKETS_WIDE

        self._m_request_s = reg.histogram(
            "relayrl_serving_request_seconds",
            "request enqueue to reply handoff (queue wait + batch close "
            "wait + dispatch share)",
            # Wide log-spaced grid (ISSUE 14 bucket audit): the old 5 s
            # top bucket pinned overload-backlogged requests in +Inf.
            buckets=LATENCY_BUCKETS_WIDE)
        self._m_evictions = {
            reason: reg.counter(
                "relayrl_serving_session_evictions_total",
                "sessions dropped from the table by cause (lru = "
                "serving.max_sessions pressure, ttl = idle past "
                "serving.session_ttl_s)",
                {"reason": reason})
            for reason in ("lru", "ttl")}
        self._m_resyncs = reg.counter(
            "relayrl_serving_session_resyncs_total",
            "sessions rebuilt from a client-shipped window (after an "
            "eviction nack or a replica re-route)")
        self._m_session_nacked = reg.counter(
            "relayrl_serving_session_nacked_total",
            "requests answered NACK_SESSION_EVICTED (client resyncs by "
            "resending its episode window)")
        import weakref

        wref = weakref.ref(self)

        def _depth():
            svc = wref()
            return None if svc is None else len(svc._queue)

        reg.gauge_fn("relayrl_serving_queue_depth", _depth,
                     "observation requests awaiting a batch close")

        def _sessions():
            svc = wref()
            return None if svc is None else len(svc._sessions)

        reg.gauge_fn("relayrl_serving_sessions", _sessions,
                     "live per-session windows in the serving table")

    @classmethod
    def from_config(cls, bundle: ModelBundle, config,
                    validate: bool = True) -> "InferenceService":
        p = config.get_serving_params()
        return cls(bundle, max_batch=p["max_batch"],
                   batch_timeout_ms=p["batch_timeout_ms"],
                   buckets=p["buckets"], queue_limit=p["queue_limit"],
                   retry_after_s=p["retry_after_s"],
                   stale_after_s=p["stale_after_s"],
                   max_sessions=p["max_sessions"],
                   session_ttl_s=p["session_ttl_s"], validate=validate)

    # -- lifecycle --
    def bind_zmq(self, addr: str) -> None:
        """Bind (or re-bind on restart) the ROUTER serving plane at
        ``addr`` — the action channel for zmq fleets AND the native
        passthrough (the C++ core has no request/response action RPC)."""
        self._zmq_addr = addr

    def start(self) -> None:
        if self._worker is not None:
            return
        self._stop.clear()
        if self._zmq_addr is not None:
            from relayrl_tpu.transport.serving import ZmqServingPlane

            self._zmq_plane = ZmqServingPlane(self._zmq_addr,
                                              self.handle_request)
            self._zmq_plane.start()
        self._worker = threading.Thread(
            target=self._serve_loop, name="inference-batcher", daemon=True)
        self._worker.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=10)
            self._worker = None
        # Parked requests answer with a retryable nack, not silence: a
        # restarting service must not wedge clients for a full timeout.
        # This must happen BEFORE the zmq plane closes — the nack rides
        # the plane's reply pipe, and a closed PUSH socket would drop it
        # silently (the plane's own stop() drains the pipe).
        with self._cond:
            pending, self._queue = list(self._queue), deque()
        for req in pending:
            self._safe_reply(req, pack_infer_nack(
                req.req_id, NACK_OVERLOADED, "inference service stopping",
                max(self.retry_after_s, 0.05)))
        if self._zmq_plane is not None:
            self._zmq_plane.stop()
            self._zmq_plane = None

    # -- model install --
    def maybe_swap(self, bundle: ModelBundle) -> bool:
        """Install a newer model (shared gate with every actor host):
        in-flight batches finish on the old version, the next batch reads
        the new one — single-version-per-batch either way."""
        from relayrl_tpu.runtime.policy_actor import apply_bundle_swap

        return apply_bundle_swap(self, bundle)

    def swap_from_wire(self, version: int, blob: bytes):
        """Wire-v2-aware swap for standalone hosts subscribing over an
        agent transport (same decode path as every actor)."""
        from relayrl_tpu.runtime.policy_actor import apply_wire_swap

        return apply_wire_swap(self, version, blob)

    def install_params(self, version: int, arch: dict, host_params) -> bool:
        """Colocated feed: the TrainingServer hands the freshly published
        host tree straight in (zero wire hops). The install owns its
        memory (the publisher's buffers keep moving) and lands on the
        serving device where one exists — the same placement rules as
        ``apply_wire_swap``."""
        jax = self._jax
        params = jax.tree.map(np.array, host_params)
        if jax.default_backend() != "cpu":
            params = jax.device_put(params)
        return self.maybe_swap(ModelBundle(version=int(version),
                                           arch=dict(arch), params=params))

    # -- request intake (transport threads) --
    def handle_request(self, payload: bytes, reply) -> InferRequest | None:
        """Transport callback: decode + enqueue (never dispatches here).
        Malformed frames answer code 0; a full queue answers the typed
        overload nack with retry-after. Returns the queued request (None
        when it was answered instead of queued) so blocking adapters can
        retract it on their own timeout. Runs on transport threads."""
        try:
            rows = unpack_infer_any(payload)
        except Exception:
            self._m_errors.inc()
            reply(pack_infer_nack(-1, 0, "malformed inference request"))
            return None
        wave = len(rows) > 1
        queued = None
        for req in rows:
            request = InferRequest(req["id"], req["req"], req["key"],
                                   req["obs"], req["mask"], reply,
                                   sid=req["sid"], rst=req["rst"],
                                   stp=req["stp"], win=req["win"],
                                   wave=wave)
            if self.submit(request):
                queued = request
        return queued

    def handle_request_blocking(self, payload: bytes) -> bytes:
        """RPC-thread adapter (grpc ``GetActions``): enqueue, then block
        this thread until its batch executes. The wait bound covers the
        worst batch close + dispatch; beyond it the client gets a
        retryable nack instead of a hung RPC — and the orphaned request
        is RETRACTED from the queue (if still there): under sustained
        overload a timed-out RPC must not leave ghost work behind that
        amplifies the very backlog that timed it out."""
        box: dict = {}
        done = threading.Event()

        def reply(b: bytes) -> None:
            box["reply"] = b
            done.set()

        request = self.handle_request(payload, reply)
        # Park bound: batch close + a stale-sweep interval, NOT a flat
        # 30 s — the caller's RPC deadline is ~request_timeout_s, and a
        # thread still parked long after it has been abandoned occupies
        # a slot in the gRPC pool the trajectory/long-poll planes share
        # (64 retrying clients would exhaust max_workers=128 and stall
        # ingest fleet-wide).
        done.wait(timeout=self.batch_timeout_s
                  + (self.stale_after_s or 5.0) + 2.0)
        if "reply" not in box and request is not None:
            with self._cond:
                try:
                    self._queue.remove(request)
                except ValueError:
                    pass  # already dispatched: its reply lands in the
                    #       abandoned box, a harmless one-off
        return box.get("reply") or pack_infer_nack(
            -1, NACK_OVERLOADED, "inference batch timed out",
            max(self.retry_after_s, 0.05))

    def submit(self, req: InferRequest) -> bool:
        """Queue one decoded request (True), or answer the overload nack
        when the queue is at ``serving.queue_limit`` (False — bounded
        queue = bounded worst-case latency; the client's retry-after
        honor is the backpressure loop)."""
        from relayrl_tpu.telemetry import trace as trace_mod

        tracer = trace_mod.get_tracer()
        if tracer.enabled:
            # Both trace fields must be final BEFORE the request becomes
            # visible to the batch worker — it reads them at gather time.
            req.trace = tracer.sample_id("serve")
            if req.trace is not None:
                req.t_enqueue_ns = time.monotonic_ns()
        with self._cond:
            if len(self._queue) >= self.queue_limit or self._stop.is_set():
                overloaded = True
            else:
                overloaded = False
                self._queue.append(req)
                self._cond.notify()
        if overloaded:
            self._m_rejected.inc()
            self._safe_reply(req, pack_infer_nack(
                req.req_id, NACK_OVERLOADED, "inference queue full",
                self.retry_after_s))
            return False
        self._m_requests.inc()
        return True

    # -- the batching loop (worker thread) --
    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            batch, reason = self._gather_batch()
            if batch:
                self._execute(batch, reason)

    def _gather_batch(self) -> tuple[list[InferRequest], str]:
        """Block for the first request, then accumulate until
        ``max_batch`` (size close) or ``batch_timeout_ms`` past the first
        request's enqueue (deadline close). The deadline anchors at
        ENQUEUE, not batch open: time a request spent queued behind the
        previous dispatch counts against its latency budget, so a loaded
        service degrades to immediate closes instead of stacking
        timeouts."""
        stale: list[InferRequest] = []

        def pop_fresh():
            # Ghost-work guard: entries older than stale_after_s were
            # abandoned by their (timed-out, already-retrying) client —
            # nack them unserved instead of double-serving every retry
            # round under backlog. Collected here, answered outside the
            # lock.
            while self._queue:
                req = self._queue.popleft()
                if (self.stale_after_s
                        and time.monotonic() - req.t_enqueue
                        > self.stale_after_s):
                    stale.append(req)
                    continue
                return req
            return None

        batch: list[InferRequest] = []
        with self._cond:
            first = pop_fresh()
            # Exit the wait as soon as there is ANYTHING to act on —
            # a fresh request to batch, or stale ones to nack (their
            # clients must not wait for unrelated traffic to arrive
            # before learning their request was shed).
            while first is None and not stale:
                if self._stop.is_set():
                    break
                self._cond.wait(0.1)
                first = pop_fresh()
            if first is not None:
                batch = [first]
                deadline = first.t_enqueue + self.batch_timeout_s
                while len(batch) < self.max_batch:
                    if self._queue:
                        got = pop_fresh()
                        if got is not None:
                            batch.append(got)
                        continue
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or self._stop.is_set():
                        break
                    self._cond.wait(remaining)
        for req in stale:
            self._m_stale.inc()
            self._safe_reply(req, pack_infer_nack(
                req.req_id, NACK_OVERLOADED, "request went stale in queue",
                self.retry_after_s))
        reason = CLOSE_SIZE if len(batch) >= self.max_batch \
            else CLOSE_DEADLINE
        return batch, reason

    def _execute(self, batch: list[InferRequest], reason: str) -> None:
        t0 = time.monotonic()
        # Close accounting rides AHEAD of the dispatch: a reply observer
        # (test, bench row) reading the counters right after its reply
        # arrives must already see this batch counted — the timing
        # histograms below stay post-dispatch because they measure it.
        self._m_batches[reason].inc()
        self._m_occupancy.observe(len(batch))
        # ONE params/version/explore read under the swap gate for the
        # whole batch: no request in it can ever be served by a different
        # model version than its batchmates (the invariant the vector
        # host enforces per dispatch, test-locked against a racing
        # swapper).
        with self._lock:
            params = self.params
            version = self.version
            explore = self._explore_kwargs
        if self._window_fn is not None:
            # Sequence policy: resolve each request against the session
            # table first (push/idempotent-retry/resync/evicted) — only
            # requests that resolved into a window row dispatch.
            batch = self._resolve_sessions(batch)
        # Mixed fleets may interleave request shapes (masked vs maskless,
        # pixel vs vector observations): group by signature, one bucketed
        # dispatch per group. Homogeneous fleets — the common case — see
        # exactly one group.
        groups: dict[tuple, list[InferRequest]] = {}
        for req in batch:
            sig = (req.obs.shape, str(req.obs.dtype), req.mask is not None,
                   str(req.key.dtype), req.key.shape)
            groups.setdefault(sig, []).append(req)
        for group in groups.values():
            try:
                if self._window_fn is not None:
                    self._dispatch_window_group(group, params, version)
                else:
                    self._dispatch_group(group, params, version, explore)
            except Exception as e:
                # One unservable group (bad shapes, dtype surprises) must
                # not take down the worker or its batchmates: every
                # member gets a retryable error reply.
                self._m_errors.inc(len(group))
                for req in group:
                    self._safe_reply(req, pack_infer_nack(
                        req.req_id, 0, f"dispatch failed: {e!r}"))
        now = time.monotonic()
        self._m_dispatch_s.observe(now - t0)
        for req in batch:
            self._m_request_s.observe(now - req.t_enqueue)
        traced = [req for req in batch if req.trace is not None]
        if traced:
            # Serve-plane hop spans for sampled requests: queue (enqueue
            # → batch gather) and dispatch (gather → reply handoff).
            from relayrl_tpu.telemetry import trace as trace_mod

            tracer = trace_mod.get_tracer()
            now_ns = time.monotonic_ns()
            t0_ns = now_ns - int((now - t0) * 1e9)
            for req in traced:
                tracer.span("serve", req.trace, "queue",
                            req.t_enqueue_ns, t0_ns,
                            agent=req.agent_id)
                tracer.span("serve", req.trace, "dispatch", t0_ns,
                            now_ns, occupancy=len(batch))

    # -- session table (serving v2; worker thread only) --
    #
    # The table has no lock of its own because the batch worker is its
    # ONLY reader and writer — transport threads just park decoded
    # requests in the queue. The gauge_fn len() read races harmlessly.
    def _resolve_sessions(self,
                          batch: list[InferRequest]) -> list[InferRequest]:
        """Turn session requests into dispatchable window rows. Answers
        everything unservable in place: no session id (error), unknown
        mid-episode session (NACK_SESSION_EVICTED — the client resyncs
        by resending its episode window), out-of-step cursor (same
        nack). A retry of an already-applied push (same ``stp``)
        recomputes from the current window WITHOUT re-pushing — with the
        client's unchanged key the recompute is bit-identical, so
        at-least-once delivery never corrupts state."""
        from relayrl_tpu.transport.base import NACK_SESSION_EVICTED

        now = time.monotonic()
        self._expire_sessions(now)
        served: list[InferRequest] = []
        for req in batch:
            try:
                if req.sid is None:
                    self._m_errors.inc()
                    self._safe_reply(req, pack_infer_nack(
                        req.req_id, 0,
                        "sequence policy serving requires a session id "
                        "(serving-v2 client; sessions are bounded by "
                        "serving.max_sessions)"))
                    continue
                sess = self._sessions.get(req.sid)
                if sess is None:
                    if not req.rst and req.win is None:
                        # Mid-episode request for a window this service
                        # does not hold (evicted, expired, or a fresh
                        # replica after re-route): typed resync nack.
                        self._m_session_nacked.inc()
                        self._safe_reply(req, pack_infer_nack(
                            req.req_id, NACK_SESSION_EVICTED,
                            "session not held (evicted or new replica) "
                            "— resend the request with the episode "
                            "window attached", self.retry_after_s))
                        continue
                    sess = _Session(self.ctx, int(self.arch["obs_dim"]),
                                    now)
                    sess.episode_step = req.stp - 1
                    self._sessions[req.sid] = sess
                    self._evict_lru()
                if req.win is not None:
                    # Client-shipped history is ground truth: rebuild
                    # wholesale (heals evictions, re-routes, and any
                    # split-brain a retry storm could leave behind).
                    self._restore_window(sess, req.win)
                    sess.episode_step = req.stp - 1
                    self._m_resyncs.inc()
                self._sessions.move_to_end(req.sid)
                sess.last_used = now
                if req.stp == sess.episode_step:
                    pass  # applied-push retry: recompute, don't re-push
                elif req.stp == sess.episode_step + 1:
                    if req.rst:
                        # Episode boundary: the new episode must not
                        # attend the previous one's observations.
                        sess.window[:] = 0.0
                        sess.length = 0
                    self._push_session(sess, req.obs)
                    sess.episode_step = req.stp
                else:
                    self._m_session_nacked.inc()
                    self._safe_reply(req, pack_infer_nack(
                        req.req_id, NACK_SESSION_EVICTED,
                        f"session cursor out of step (held "
                        f"{sess.episode_step}, got {req.stp}) — resend "
                        f"the request with the episode window attached",
                        self.retry_after_s))
                    continue
                req.window_row = sess.window
                req.window_t = sess.length
                served.append(req)
            except Exception as e:
                # Malformed session payload (wrong obs_dim, bad window
                # shape): a per-request error, never a dead worker.
                self._m_errors.inc()
                self._safe_reply(req, pack_infer_nack(
                    req.req_id, 0, f"session resolve failed: {e!r}"))
        return served

    def _restore_window(self, sess: _Session, win: np.ndarray) -> None:
        rows = np.asarray(win, np.float32).reshape(
            (-1, sess.window.shape[1]))[-self.ctx:]
        sess.window[:] = 0.0
        sess.window[:rows.shape[0]] = rows
        sess.length = rows.shape[0]

    @staticmethod
    def _push_session(sess: _Session, obs: np.ndarray) -> None:
        # The parity contract requires the served window to roll the way
        # a local one does — so advance through the shared rule.
        sess.length, _ = push_window(sess.window, sess.length, obs)

    def _evict_lru(self) -> None:
        from relayrl_tpu import telemetry

        while len(self._sessions) > self.max_sessions:
            sid, _ = self._sessions.popitem(last=False)
            self._m_evictions["lru"].inc()
            telemetry.emit("serving_session_evicted", session=sid,
                           reason="lru")

    def _expire_sessions(self, now: float) -> None:
        if not self.session_ttl_s:
            return
        from relayrl_tpu import telemetry

        horizon = now - self.session_ttl_s
        while self._sessions:
            sid, sess = next(iter(self._sessions.items()))
            if sess.last_used >= horizon:
                break  # LRU order: everything behind is fresher
            self._sessions.popitem(last=False)
            self._m_evictions["ttl"].inc()
            telemetry.emit("serving_session_evicted", session=sid,
                           reason="ttl")

    def _dispatch_window_group(self, group: list[InferRequest], params,
                               version: int) -> None:
        jnp = self._jax.numpy
        n = len(group)
        bucket = pick_bucket(n, self.buckets)

        def padded(stack: np.ndarray) -> np.ndarray:
            if bucket == n:
                return stack
            return np.concatenate(
                [stack, np.repeat(stack[-1:], bucket - n, axis=0)])

        keys = padded(np.stack([r.key for r in group]))
        # np.stack COPIES the session windows at dispatch time, so the
        # device sees a stable snapshot even though the table's arrays
        # keep rolling under later batches.
        windows = padded(np.stack([r.window_row for r in group]))
        ts = padded(np.asarray([r.window_t for r in group], np.int32))
        masks = None
        if group[0].mask is not None:
            masks = padded(np.stack([r.mask for r in group]))
        acts, aux, next_keys = self._window_fn(
            params, jnp.asarray(keys), windows, ts, masks)
        self._send_group_replies(group, version, np.asarray(acts),
                                 np.asarray(next_keys),
                                 {k: np.asarray(v) for k, v in aux.items()},
                                 ctx=self.ctx)

    def _dispatch_group(self, group: list[InferRequest], params,
                        version: int, explore: dict) -> None:
        jnp = self._jax.numpy
        n = len(group)
        bucket = pick_bucket(n, self.buckets)

        def padded(stack: np.ndarray) -> np.ndarray:
            # Pad to the bucket by repeating the last row: vmap rows are
            # independent, so pad content cannot perturb real rows (the
            # padding-invariance test locks it); repeating a REAL row
            # keeps dtypes/shapes trivially right.
            if bucket == n:
                return stack
            return np.concatenate(
                [stack, np.repeat(stack[-1:], bucket - n, axis=0)])

        keys = padded(np.stack([r.key for r in group]))
        obs = padded(np.stack([r.obs for r in group]))
        masks = None
        if group[0].mask is not None:
            masks = padded(np.stack([r.mask for r in group]))
        acts, aux, next_keys = self._batched_fn(
            params, jnp.asarray(keys), obs, masks, explore)
        self._send_group_replies(group, version, np.asarray(acts),
                                 np.asarray(next_keys),
                                 {k: np.asarray(v) for k, v in aux.items()})

    def _send_group_replies(self, group: list[InferRequest], version: int,
                            acts_np: np.ndarray, keys_np: np.ndarray,
                            aux_np: dict, ctx: int | None = None) -> None:
        """Reply fan-out with wave coalescing: batchmates that arrived on
        the same wave frame (one shared reply pipe) leave as ONE stacked
        frame per dispatch batch; everything else — singles, nacks, lone
        wave survivors — rides the per-request wire. The per-reply pack
        cost is the serving plane's largest per-step Python cost
        (~50us), so coalescing here is half the wave wire's win."""
        singles: list[int] = []
        waves: dict[int, list[int]] = {}
        order: list[int] = []
        for i, req in enumerate(group):
            if req.wave:
                k = id(req.reply)
                if k not in waves:
                    waves[k] = []
                    order.append(k)
                waves[k].append(i)
            else:
                singles.append(i)
        for k in order:
            idxs = waves[k]
            if len(idxs) == 1:
                singles.append(idxs[0])
                continue
            reqs = [group[i] for i in idxs]
            sel = np.asarray(idxs)
            payload = pack_reply_wave(
                [r.req_id for r in reqs], version, acts_np[sel],
                keys_np[sel], {a: v[sel] for a, v in aux_np.items()},
                ctx=ctx)
            self._safe_reply(reqs[0], payload)
        for i in singles:
            req = group[i]
            # np.asarray on the indexed rows: a stacked [N] column
            # indexes to a numpy scalar, and the wire must carry the 0-d
            # ndarray's exact dtype (the vector-host float64 lesson).
            self._safe_reply(req, pack_action_reply(
                req.req_id, version, np.asarray(acts_np[i]), keys_np[i],
                {a: np.asarray(v[i]) for a, v in aux_np.items()},
                ctx=ctx))

    @staticmethod
    def _safe_reply(req: InferRequest, payload: bytes) -> None:
        """Reply-delivery isolation: one dead client connection must not
        take down the batch that served its neighbors."""
        try:
            req.reply(payload)
        except Exception as e:
            print(f"[InferenceService] reply delivery failed: {e!r}",
                  flush=True)

    def accounting(self) -> dict:
        """Bench/drill evidence block (mirrors the registry counters)."""
        return {
            "queue_depth": len(self._queue),
            "max_batch": self.max_batch,
            "batch_timeout_ms": self.batch_timeout_s * 1000.0,
            "buckets": list(self.buckets),
            "sessions": len(self._sessions),
            "max_sessions": self.max_sessions,
            "ctx": self.ctx,
        }


class RemoteActorClient:
    """Thin-client actor (``actor.host_mode: "remote"``): holds NO
    params, NO model subscription, NO swap gate — every action is a
    request/response round-trip to an :class:`InferenceService`. The
    trajectory plane (Trajectory assembly, spool sequence tags, transport
    envelopes) is the standard actor plane, byte-identical on the wire.

    The client carries its PRNG key and round-trips it through the
    service (which splits it inside the jitted dispatch, exactly
    ``_fuse_rng``), so for the same ``seed`` the served action stream is
    bit-identical to a local ``PolicyActor(seed=seed)`` holding the same
    params version — the parity contract tests/test_serving.py locks.

    Overload nacks honor the server's ``retry_after_s`` without charging
    the circuit breaker (the server is alive and answered — the spool's
    nack lesson); transport failures back off under the shared
    ``transport.retry`` policy behind a breaker, so a killed service
    never wedges the env loop in a hot retry spin.
    """

    def __init__(
        self,
        config_path: str | None = None,
        server_type: str = "zmq",
        seed: int | None = None,
        identity: str | None = None,
        start: bool = True,
        handshake_timeout_s: float = 60.0,
        **addr_overrides,
    ):
        import os

        from relayrl_tpu.config import ConfigLoader

        self.config = ConfigLoader(None, config_path)
        from relayrl_tpu import faults, telemetry

        telemetry.configure_from_config(self.config)
        faults.maybe_install_from_env()
        self._fault_infer = faults.site("agent.infer")
        self.server_type = server_type
        self._addr_overrides = addr_overrides
        self._identity = identity
        self._handshake_timeout_s = handshake_timeout_s
        self._seed = os.getpid() if seed is None else seed
        serving = self.config.get_serving_params()
        self._request_timeout_s = serving["request_timeout_s"]
        self._infer_deadline_s = serving["infer_deadline_s"]
        self._lock = threading.Lock()
        self._req_counter = 0
        self.version = -1  # latest service version that answered us
        # Serving-v2 session state: every request carries a session id
        # (the transport identity) + a monotonic push cursor, so sequence
        # policies serve from a SERVER-side rolling window. The client
        # keeps a small mirror of the current episode's observations —
        # the resync source after a NACK_SESSION_EVICTED or a replica
        # re-route — bounded to the service's window length once a reply
        # names it. Stateless policies answer without a ``ctx`` field and
        # the mirror shuts off.
        self._session_id = None
        self._session_step = 0
        self._episode_start = True
        self._mirror: list | None = []
        # Horizontal serving: session-affine home replica out of
        # serving.replicas, rotated after repeated transport failures
        # (the new replica answers NACK_SESSION_EVICTED and the resync
        # machinery rebuilds the session there).
        self._replica_addrs: list[str] | None = None
        self._replica_idx = 0
        self._replica_fail_streak = 0
        self._serving_overrides: dict = {}
        self.transport = None
        self.spool = None
        self._serving = None
        self._breaker = None
        self._retry = None
        self._fleet_emitter = None
        self.trajectory = Trajectory(
            max_length=self.config.get_max_traj_length(),
            on_send=self._send_traj)
        import jax

        self._rng = np.asarray(jax.random.PRNGKey(self._seed))
        reg = telemetry.get_registry()
        self._m_steps = reg.counter(
            "relayrl_actor_env_steps_total",
            "policy steps served (one per env step per lane)")
        from relayrl_tpu.telemetry.core import LATENCY_BUCKETS_WIDE

        self._m_request_s = reg.histogram(
            "relayrl_serving_client_request_seconds",
            "one action round-trip on the client (send to decoded reply, "
            "retries included)",
            # Wide grid (ISSUE 14 bucket audit): retries through an open
            # breaker legitimately stack past the old 5 s top bucket.
            buckets=LATENCY_BUCKETS_WIDE)
        self._m_retries = reg.counter(
            "relayrl_serving_client_retries_total",
            "inference request attempts beyond the first")
        self._m_nacked = reg.counter(
            "relayrl_serving_client_nacked_total",
            "overload nacks honored (slept retry_after_s, no breaker "
            "charge)")
        self._m_resyncs = reg.counter(
            "relayrl_serving_client_resyncs_total",
            "session resyncs performed (episode window resent after a "
            "NACK_SESSION_EVICTED or replica re-route)")
        self._m_reroutes = reg.counter(
            "relayrl_serving_client_reroutes_total",
            "replica re-routes after persistent transport failures on "
            "the session-affine home replica")
        self.active = False
        if start:
            self.enable_agent()

    # -- lifecycle (Agent-compatible surface) --
    def enable_agent(self) -> None:
        if self.active:
            return
        from relayrl_tpu.transport import make_agent_transport
        from relayrl_tpu.transport.retry import (
            RetryPolicy,
            breaker_from_config,
        )
        from relayrl_tpu.transport.serving import make_serving_client

        overrides = dict(self._addr_overrides)
        overrides.setdefault("negotiate_window_s",
                             min(self._handshake_timeout_s * 0.5, 30.0))
        if self._identity is not None:
            overrides.setdefault("identity", self._identity)
        serving_overrides = {
            k: overrides.pop(k)
            for k in ("serving_addr", "serving_plane", "serving_addrs",
                      "stream")
            if k in overrides}
        self.transport = make_agent_transport(
            self.server_type, self.config, **overrides)
        self._session_id = self.transport.identity
        # Horizontal serving: an explicit serving_addrs override or the
        # serving.replicas config names N replica endpoints; this
        # session's home replica is hash(session_id) % N (stable crc32 —
        # affinity must agree across client restarts). zmq-plane only:
        # the grpc in-band plane rides the agent channel.
        replicas = serving_overrides.pop("serving_addrs", None) \
            or self.config.get_serving_params()["replicas"]
        plane = serving_overrides.get("serving_plane") or (
            "grpc" if self.server_type == "grpc" else "zmq")
        if replicas and plane != "grpc" \
                and "serving_addr" not in serving_overrides:
            import zlib

            self._replica_addrs = [str(a) for a in replicas]
            self._replica_idx = (zlib.crc32(self._session_id.encode())
                                 % len(self._replica_addrs))
            serving_overrides["serving_addr"] = \
                self._replica_addrs[self._replica_idx]
        self._serving_overrides = dict(serving_overrides)
        # No fetch_model: the whole point is that this actor never holds
        # a model. Registration still announces the logical agent.
        try:
            self.transport.register(self.transport.identity, timeout_s=10.0)
        except Exception as e:
            print(f"[RemoteActorClient] registration failed (continuing "
                  f"unregistered): {e!r}", flush=True)
        self._bind_spool()
        self.transport.on_reconnect = self._handle_reconnect
        retry_cfg = self.config.get_transport_params()["retry"]
        self._retry = RetryPolicy.from_dict(retry_cfg)
        if self._breaker is None:
            self._breaker = breaker_from_config(
                f"infer:{self._identity or 'remote'}", retry_cfg)
        self._serving = make_serving_client(
            self.server_type, self.config, transport=self.transport,
            **serving_overrides)
        from relayrl_tpu.runtime.agent import _start_fleet_emitter

        self._fleet_emitter = _start_fleet_emitter(self, "client")
        self.active = True
        from relayrl_tpu import telemetry

        telemetry.emit("agent_register", agent_id=self.transport.identity,
                       side="agent", mode="remote")

    def disable_agent(self) -> None:
        if not self.active:
            return
        from relayrl_tpu.runtime.agent import _close_fleet_emitter

        _close_fleet_emitter(self)
        if self.spool is not None:
            self.spool.send_fn = None
        if self._serving is not None:
            self._serving.close()
            self._serving = None
        self.transport.close()
        self.transport = None
        self.active = False

    def _bind_spool(self) -> None:
        from relayrl_tpu.runtime.agent import _bind_spool_impl

        _bind_spool_impl(self, self._identity or "remote")

    def _handle_reconnect(self) -> None:
        from relayrl_tpu.runtime.agent import _handle_reconnect_impl

        _handle_reconnect_impl(self, [self.transport.identity])

    def _send_traj(self, payload: bytes) -> None:
        # Trajectory tracing parity with Agent._send_traj: the thin
        # client's episodes draw trace contexts too (env hop = the
        # round-trip-served production window).
        from relayrl_tpu.runtime.agent import _trace_emit, _trace_send_span

        traj = self.trajectory
        ctx = _trace_emit(self.transport.identity, traj.born_ns,
                          traj.encode_t0_ns, traj.encode_t1_ns,
                          self.version)
        t0 = 0
        if ctx is not None:
            t0 = time.monotonic_ns()
        if self.spool is not None:
            self.spool.send(payload, self.transport.identity,
                            trace=None if ctx is None else ctx.encode())
            _trace_send_span(ctx, self.transport.identity, t0)
        else:
            from relayrl_tpu.transport.base import IngestNack, tag_agent_trace

            try:
                self.transport.send_trajectory(
                    payload,
                    agent_id=(None if ctx is None else tag_agent_trace(
                        self.transport.identity, ctx.encode())))
                _trace_send_span(ctx, self.transport.identity, t0)
            except IngestNack:
                pass  # guardrail verdict, spool-less: drop (see Agent)

    # -- action API (PolicyActor-shaped) --
    def request_for_action(self, obs, mask=None,
                           reward: float = 0.0) -> ActionRecord:
        """One served action: ship the observation + current PRNG key,
        append the returned action to the trajectory. Reward credit
        semantics identical to ``PolicyActor.request_for_action`` (the
        reward lands on the PREVIOUS record)."""
        self._require_active()
        from relayrl_tpu.runtime.policy_actor import normalize_obs

        # Byte frames stay bytes on the wire, everything else float32 —
        # the shared rule every tier uses (the parity contract rides on
        # it staying ONE body).
        obs = normalize_obs(obs)
        mask_arr = None if mask is None else np.asarray(mask, np.float32)
        with self._lock:
            if reward and self.trajectory.get_actions():
                self.trajectory.get_actions()[-1].update_reward(
                    float(reward))
            # jaxlint: disable=LOCK02 - per-client lock; the env loop is serial, blocking here IS the backpressure
            act, aux = self._infer(obs, mask_arr)
            record = ActionRecord(
                obs=obs, act=act, mask=mask_arr,
                rew=0.0,  # filled by the NEXT request / terminal marker
                data=aux, done=False)
            self.trajectory.add_action(record, send_if_done=True)
        self._m_steps.inc()
        return record

    def flag_last_action(self, reward: float = 0.0, truncated: bool = False,
                         final_obs=None, terminated: bool | None = None,
                         final_mask=None) -> None:
        """Terminal marker — same semantics as PolicyActor's (terminated
        beats truncated, the bootstrap final_obs rides the marker). The
        next request carries the episode-reset flag so the SERVER-side
        session window zeroes at the boundary, exactly where a local
        windowed actor zeroes its own."""
        self._require_active()
        if terminated:
            truncated = False
        with self._lock:
            self._episode_start = True
            if self._mirror is not None:
                self._mirror = []
            record = ActionRecord(
                obs=(None if final_obs is None
                     else np.asarray(final_obs, np.float32)),
                mask=(None if final_mask is None
                      else np.asarray(final_mask, np.float32)),
                rew=float(reward), done=True, truncated=bool(truncated))
            self.trajectory.add_action(record, send_if_done=True)

    def record_action(self, action: ActionRecord) -> None:
        self._require_active()
        with self._lock:
            self.trajectory.add_action(action, send_if_done=True)

    def _infer(self, obs: np.ndarray, mask) -> tuple[np.ndarray, dict]:
        """One request/response round-trip with overload + failure
        handling (lock held — the env loop is serial per client):

        * overload nack → honor ``retry_after_s``, no breaker charge;
        * session-evicted nack → resend with the episode window attached
          (resync, not failure — no breaker charge, no backoff);
        * timeout / connection error → breaker charge + jittered backoff
          under ``transport.retry`` (a dead service opens the breaker and
          the loop waits out half-open probes instead of hot-spinning);
          persistent failures on a replica fleet rotate to the next
          replica (its eviction nack then triggers the resync above);
        * total budget ``serving.infer_deadline_s`` → RuntimeError (the
          env loop's caller decides; nothing is appended mid-failure).
        """
        self._req_counter += 1
        req_id = self._req_counter
        stp = self._session_step + 1
        rst = self._episode_start

        def build(with_win: bool) -> bytes:
            win = None
            if with_win and self._mirror:
                win = np.stack(self._mirror)
            return pack_infer_request(
                self.transport.identity, req_id, self._rng, obs, mask,
                session=self._session_id, reset=rst, window=win, step=stp)

        clean = build(False)
        first_attempt = clean
        dropped_first = False
        if self._fault_infer is not None:
            # chaos plane (agent.infer): the injection applies to the
            # FIRST attempt only — drop surfaces as a timeout → retry,
            # corrupt dies in the service's decode guard → retry, delay
            # sleeps here. Retries always carry the clean payload (one
            # fault per op, the plan's per-op contract — a corrupted
            # attempt retried corrupted forever would turn a 20%-corrupt
            # drill into guaranteed deadline exhaustion).
            parts = self._fault_infer.inject(clean)
            if not parts:
                dropped_first = True
            else:
                delay_s, first_attempt = parts[-1]
                if delay_s > 0:
                    time.sleep(delay_s)
        deadline = time.monotonic() + self._infer_deadline_s
        attempt = 0
        t0 = time.monotonic()
        last_error = ""
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise RuntimeError(
                    f"inference request exhausted its "
                    f"{self._infer_deadline_s:.0f}s budget "
                    f"(service down? breaker={self._breaker.state}"
                    f"{f'; last error: {last_error}' if last_error else ''})")
            if dropped_first:
                # fault-dropped first attempt: exactly a timeout's shape
                dropped_first = False
                self._note_failure(attempt, remaining)
                attempt += 1
                continue
            if not self._breaker.allow():
                time.sleep(min(0.2, remaining))
                continue
            try:
                reply = self._serving.request(
                    first_attempt if attempt == 0 else clean, req_id,
                    min(self._request_timeout_s, remaining))
            except (TimeoutError, ConnectionError, OSError):
                self._breaker.record_failure()
                self._replica_fail_streak += 1
                if self._replica_fail_streak >= 2 \
                        and self._rotate_replica():
                    # Replica death: session-affine re-route. The next
                    # replica will not hold this session and nacks
                    # SESSION_EVICTED — the resync branch below rebuilds
                    # it from the client's episode mirror.
                    self._replica_fail_streak = 0
                self._note_failure(attempt, deadline - time.monotonic())
                attempt += 1
                continue
            self._breaker.record_success()
            self._replica_fail_streak = 0
            code = reply["code"]
            if code == NACK_OVERLOADED:
                # The service is ALIVE and shed us: honor the hint, keep
                # the breaker closed (the IngestNack lesson).
                self._m_nacked.inc()
                time.sleep(min(max(reply["retry_after_s"], 0.001),
                               max(0.0, deadline - time.monotonic())))
                continue
            from relayrl_tpu.transport.base import NACK_SESSION_EVICTED

            if code == NACK_SESSION_EVICTED:
                # Resync, not failure: resend the SAME request with the
                # episode window attached (the service rebuilds the
                # session wholesale from it). No breaker charge, no
                # backoff — the service is alive and asked for exactly
                # this.
                self._m_resyncs.inc()
                clean = first_attempt = build(True)
                attempt += 1
                continue
            if code == NACK_UNAVAILABLE:
                # PERMANENT: the endpoint answered but no inference
                # service is installed (serving.enabled false) — a
                # misconfiguration, not an outage; retrying would only
                # bury the pointed error under a deadline exhaustion.
                raise RuntimeError(
                    f"inference unavailable: {reply['error']}")
            if code != NACK_OK or "act" not in reply:
                # code-0 error (malformed/failed dispatch): retryable —
                # the chaos corrupt drill lands here.
                last_error = reply.get("error") or last_error
                self._note_failure(attempt, deadline - time.monotonic())
                attempt += 1
                continue
            self._rng = np.frombuffer(
                reply["key"], dtype=self._rng.dtype).copy()
            self.version = reply["ver"]
            self._session_step = stp
            self._episode_start = False
            ctx = reply.get("ctx")
            if ctx is None:
                # Stateless policy: the service keeps no window for us,
                # so there is nothing a resync could ever need.
                self._mirror = None
            elif self._mirror is not None:
                # Mirror AFTER success — during eviction-resync retries
                # the mirror must still exclude the current observation
                # (it rides the request itself). Bounded to the service
                # window: older rows can never matter to a resync.
                self._mirror.append(obs)
                if len(self._mirror) > ctx:
                    del self._mirror[:len(self._mirror) - ctx]
            self._m_request_s.observe(time.monotonic() - t0)
            return reply["act"], reply["aux"]

    def _rotate_replica(self) -> bool:
        """Re-route this session to the next replica (replica-fleet
        clients only). Returns True when the serving channel actually
        moved."""
        if not self._replica_addrs or len(self._replica_addrs) < 2:
            return False
        from relayrl_tpu.transport.serving import make_serving_client

        self._replica_idx = (self._replica_idx + 1) \
            % len(self._replica_addrs)
        addr = self._replica_addrs[self._replica_idx]
        overrides = dict(self._serving_overrides)
        overrides["serving_addr"] = addr
        old, self._serving = self._serving, make_serving_client(
            self.server_type, self.config, transport=self.transport,
            **overrides)
        if old is not None:
            try:
                old.close()
            except Exception:
                pass
        self._m_reroutes.inc()
        from relayrl_tpu import telemetry

        telemetry.emit("serving_replica_reroute",
                       agent_id=self._session_id, addr=addr)
        return True

    def _note_failure(self, attempt: int, remaining: float) -> None:
        self._m_retries.inc()
        if remaining > 0:
            time.sleep(min(self._retry.delay(attempt), remaining))

    @property
    def model_version(self) -> int:
        """Latest service-side params version that served this client an
        action (-1 before the first reply) — the thin client's analogue
        of an actor's installed version."""
        return self.version

    def _require_active(self) -> None:
        if not self.active or self._serving is None:
            raise RuntimeError(
                "remote actor client is not active (call enable_agent())")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.disable_agent()


class MultiplexedRemoteClient:
    """Thin-client host multiplexing N env lanes over the streaming
    serving channel — serving v2's answer to the lock-step plateau: one
    process keeps up to ``serving.stream_window`` requests in flight per
    replica connection (out-of-order replies legal, req-id matched), so
    the service sees dense batches from a single client instead of one
    request per Python round-trip.

    Each lane is an independent logical actor: its own session id
    (server-side rolling window for sequence policies), PRNG key
    (``PRNGKey(seed + lane)`` — lane i's action stream is bit-identical
    to a local ``PolicyActor(seed=seed + lane)`` at the same params
    version), trajectory, and episode mirror. Lanes are session-affine
    across ``serving.replicas`` by ``crc32(session_id) % N``; a replica
    death re-routes its lanes and the eviction-nack resync rebuilds
    their windows on the new home.
    """

    def __init__(
        self,
        config_path: str | None = None,
        server_type: str = "zmq",
        lanes: int = 1,
        seed: int | None = None,
        identity: str | None = None,
        start: bool = True,
        handshake_timeout_s: float = 60.0,
        **addr_overrides,
    ):
        import os

        from relayrl_tpu.config import ConfigLoader

        self.config = ConfigLoader(None, config_path)
        from relayrl_tpu import telemetry

        telemetry.configure_from_config(self.config)
        self.server_type = server_type
        self.lanes = max(1, int(lanes))
        self._addr_overrides = addr_overrides
        self._identity = identity
        self._handshake_timeout_s = handshake_timeout_s
        self._seed = os.getpid() if seed is None else seed
        serving = self.config.get_serving_params()
        self._request_timeout_s = serving["request_timeout_s"]
        self._infer_deadline_s = serving["infer_deadline_s"]
        self._stream_window = serving["stream_window"]
        self._retry_after_default = serving["retry_after_s"]
        self._lock = threading.Lock()
        self._req_counter = 0
        self.version = -1
        self.transport = None
        self.spool = None
        self._clients: list = []       # one streaming client per replica
        self._lane_client: list[int] = []  # lane -> client index
        self._retry = None
        self._fleet_emitter = None
        import jax

        self._keys = [np.asarray(jax.random.PRNGKey(self._seed + i))
                      for i in range(self.lanes)]
        self._session_steps = [0] * self.lanes
        self._episode_starts = [True] * self.lanes
        self._mirrors: list = [[] for _ in range(self.lanes)]
        self._sids: list[str] = []
        self.trajectories: list[Trajectory] = []
        reg = telemetry.get_registry()
        self._m_steps = reg.counter(
            "relayrl_actor_env_steps_total",
            "policy steps served (one per env step per lane)")
        self._m_retries = reg.counter(
            "relayrl_serving_client_retries_total",
            "inference request attempts beyond the first")
        self._m_nacked = reg.counter(
            "relayrl_serving_client_nacked_total",
            "overload nacks honored (slept retry_after_s, no breaker "
            "charge)")
        self._m_resyncs = reg.counter(
            "relayrl_serving_client_resyncs_total",
            "session resyncs performed (episode window resent after a "
            "NACK_SESSION_EVICTED or replica re-route)")
        self.active = False
        if start:
            self.enable_agent()

    # -- lifecycle --
    def enable_agent(self) -> None:
        if self.active:
            return
        import zlib

        from relayrl_tpu.transport import make_agent_transport
        from relayrl_tpu.transport.retry import RetryPolicy
        from relayrl_tpu.transport.serving import make_serving_client

        overrides = dict(self._addr_overrides)
        overrides.setdefault("negotiate_window_s",
                             min(self._handshake_timeout_s * 0.5, 30.0))
        if self._identity is not None:
            overrides.setdefault("identity", self._identity)
        serving_overrides = {
            k: overrides.pop(k)
            for k in ("serving_addr", "serving_plane", "serving_addrs")
            if k in overrides}
        self.transport = make_agent_transport(
            self.server_type, self.config, **overrides)
        self._retry = RetryPolicy.from_dict(
            self.config.get_transport_params()["retry"])
        self._sids = [f"{self.transport.identity}#L{i:03d}"
                      for i in range(self.lanes)]
        self.trajectories = [
            Trajectory(max_length=self.config.get_max_traj_length(),
                       on_send=(lambda p, sid=sid: self._send_traj(sid, p)))
            for sid in self._sids]
        try:
            self.transport.register(self.transport.identity,
                                    timeout_s=10.0)
            for sid in self._sids:
                self.transport.register(sid, timeout_s=10.0)
        except Exception as e:
            print(f"[MultiplexedRemoteClient] registration failed "
                  f"(continuing unregistered): {e!r}", flush=True)
        self._bind_spool()
        # One streaming client per replica; lanes route session-affine.
        replicas = serving_overrides.pop("serving_addrs", None) \
            or self.config.get_serving_params()["replicas"]
        plane = serving_overrides.get("serving_plane") or (
            "grpc" if self.server_type == "grpc" else "zmq")
        if replicas and plane != "grpc":
            for addr in replicas:
                ov = dict(serving_overrides)
                ov.update(serving_addr=str(addr), stream=True)
                self._clients.append(make_serving_client(
                    self.server_type, self.config,
                    transport=self.transport, **ov))
        else:
            ov = dict(serving_overrides)
            ov["stream"] = True
            self._clients.append(make_serving_client(
                self.server_type, self.config, transport=self.transport,
                **ov))
        self._lane_client = [
            zlib.crc32(sid.encode()) % len(self._clients)
            for sid in self._sids]
        from relayrl_tpu.runtime.agent import _start_fleet_emitter

        self._fleet_emitter = _start_fleet_emitter(self, "client")
        self.active = True
        from relayrl_tpu import telemetry

        telemetry.emit("agent_register", agent_id=self.transport.identity,
                       side="agent", mode="remote-mux")

    def disable_agent(self) -> None:
        if not self.active:
            return
        from relayrl_tpu.runtime.agent import _close_fleet_emitter

        _close_fleet_emitter(self)
        if self.spool is not None:
            self.spool.send_fn = None
        for client in self._clients:
            try:
                client.close()
            except Exception:
                pass
        self._clients = []
        self.transport.close()
        self.transport = None
        self.active = False

    def _bind_spool(self) -> None:
        from relayrl_tpu.runtime.agent import _bind_spool_impl

        _bind_spool_impl(self, self._identity or "remote-mux")

    def _send_traj(self, sid: str, payload: bytes) -> None:
        if self.spool is not None:
            self.spool.send(payload, sid)
            return
        from relayrl_tpu.transport.base import IngestNack

        try:
            self.transport.send_trajectory(payload, agent_id=sid)
        except IngestNack:
            pass  # guardrail verdict, spool-less: drop (see Agent)

    @property
    def inflight_high_water(self) -> int:
        """Deepest concurrent request pipeline seen across replica
        connections — the streaming-actually-streams evidence the
        serving smoke asserts (≥2 means the lock-step era is over)."""
        return max((c.inflight_high_water for c in self._clients),
                   default=0)

    # -- action API (vector-shaped) --
    def request_for_actions(self, obs_batch, masks=None,
                            rewards=None) -> list[ActionRecord]:
        """One served action per lane, pipelined: every lane's request is
        submitted before any reply is awaited, so up to
        ``serving.stream_window`` requests ride each replica connection
        concurrently. Reward credit semantics are per-lane identical to
        ``PolicyActor.request_for_action``."""
        self._require_active()
        from relayrl_tpu.runtime.policy_actor import normalize_obs

        n = len(obs_batch)
        if n != self.lanes:
            raise ValueError(f"expected {self.lanes} lane observations, "
                             f"got {n}")
        obs_list = [normalize_obs(o) for o in obs_batch]
        mask_list = [None if masks is None or masks[i] is None
                     else np.asarray(masks[i], np.float32)
                     for i in range(n)]
        with self._lock:
            if rewards is not None:
                for i in range(n):
                    if rewards[i] and self.trajectories[i].get_actions():
                        self.trajectories[i].get_actions()[-1] \
                            .update_reward(float(rewards[i]))
            # jaxlint: disable=LOCK02 - per-client lock; the driving loop is serial, blocking here IS the backpressure
            replies = self._infer_all(obs_list, mask_list)
            records = []
            for i in range(n):
                act, aux = replies[i]
                record = ActionRecord(
                    obs=obs_list[i], act=act, mask=mask_list[i],
                    rew=0.0, data=aux, done=False)
                self.trajectories[i].add_action(record, send_if_done=True)
                records.append(record)
        self._m_steps.inc(n)
        return records

    def flag_last_action(self, lane: int, reward: float = 0.0,
                         truncated: bool = False, final_obs=None,
                         terminated: bool | None = None,
                         final_mask=None) -> None:
        """Per-lane terminal marker (vector-host semantics): ships the
        lane's episode and schedules the session-window reset flag for
        its next request."""
        self._require_active()
        if terminated:
            truncated = False
        with self._lock:
            self._episode_starts[lane] = True
            if self._mirrors[lane] is not None:
                self._mirrors[lane] = []
            record = ActionRecord(
                obs=(None if final_obs is None
                     else np.asarray(final_obs, np.float32)),
                mask=(None if final_mask is None
                      else np.asarray(final_mask, np.float32)),
                rew=float(reward), done=True, truncated=bool(truncated))
            self.trajectories[lane].add_action(record, send_if_done=True)

    # -- the pipelined infer engine --
    def _build(self, lane: int, obs, mask, req_id: int,
               with_win: bool) -> bytes:
        win = None
        if with_win and self._mirrors[lane]:
            win = np.stack(self._mirrors[lane])
        return pack_infer_request(
            self._sids[lane], req_id, self._keys[lane], obs, mask,
            session=self._sids[lane], reset=self._episode_starts[lane],
            window=win, step=self._session_steps[lane] + 1)

    def _infer_all(self, obs_list, mask_list) -> list:
        """Submit every lane, then collect with per-lane retry handling
        (overload → honor retry-after; evicted → resync with the lane
        mirror; timeout/stream-break → resubmit under fresh req ids,
        rotating dead replicas). Lanes are chunked into waves of
        ``stream_window`` per replica connection so the in-flight depth
        stays bounded."""
        deadline = time.monotonic() + self._infer_deadline_s
        results: list = [None] * len(obs_list)
        # Wave chunking per client connection.
        by_client: dict[int, list[int]] = {}
        for lane in range(len(obs_list)):
            by_client.setdefault(self._lane_client[lane], []).append(lane)
        waves: list[list[int]] = []
        w = max(1, int(self._stream_window))
        round_idx = 0
        while True:
            wave = []
            for lanes_ in by_client.values():
                wave.extend(lanes_[round_idx * w:(round_idx + 1) * w])
            if not wave:
                break
            waves.append(wave)
            round_idx += 1
        for wave in waves:
            inflight: dict[int, tuple] = self._submit_wave(
                wave, obs_list, mask_list)
            attempt = 0
            while inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    for lane, (waiter, _) in inflight.items():
                        self._clients[self._lane_client[lane]] \
                            .cancel(waiter.req_id)
                    raise RuntimeError(
                        f"multiplexed inference exhausted its "
                        f"{self._infer_deadline_s:.0f}s budget with "
                        f"{len(inflight)} lanes unserved")
                retry_lanes: list[tuple[int, bool]] = []
                nap = 0.0
                for lane in list(inflight):
                    waiter, req_id = inflight.pop(lane)
                    client = self._clients[self._lane_client[lane]]
                    try:
                        reply = client.wait(
                            waiter, min(self._request_timeout_s,
                                        max(0.05, remaining)))
                    except (TimeoutError, ConnectionError, OSError):
                        self._m_retries.inc()
                        if len(self._clients) > 1:
                            # Re-route: next replica; its eviction nack
                            # resyncs the session there.
                            self._lane_client[lane] = \
                                (self._lane_client[lane] + 1) \
                                % len(self._clients)
                        retry_lanes.append((lane, False))
                        continue
                    outcome = self._apply_reply(lane, obs_list[lane],
                                                reply)
                    if outcome == "ok":
                        results[lane] = (reply["act"], reply["aux"])
                    elif outcome == "resync":
                        retry_lanes.append((lane, True))
                    else:  # overloaded (or retryable error)
                        nap = max(nap, reply.get("retry_after_s")
                                  or self._retry_after_default)
                        retry_lanes.append((lane, False))
                if nap > 0:
                    time.sleep(min(nap,
                                   max(0.0,
                                       deadline - time.monotonic())))
                elif retry_lanes:
                    time.sleep(min(self._retry.delay(attempt), 0.2))
                for lane, with_win in retry_lanes:
                    inflight[lane] = self._submit_lane(
                        lane, obs_list[lane], mask_list[lane],
                        with_win=with_win)
                if retry_lanes:
                    attempt += 1
        return results

    def _submit_lane(self, lane: int, obs, mask,
                     with_win: bool) -> tuple:
        self._req_counter += 1
        req_id = self._req_counter
        payload = self._build(lane, obs, mask, req_id, with_win)
        waiter = self._clients[self._lane_client[lane]].submit(
            payload, req_id)
        return waiter, req_id

    def _submit_wave(self, lanes: list[int], obs_list,
                     mask_list) -> dict[int, tuple]:
        """Initial submits, coalesced: one ``pack_infer_wave`` frame per
        replica connection with stacked obs/key blocks — the wire-cost
        amortization that lets a saturated-core fleet clear the
        lock-step plateau. Falls back to per-lane frames for clients
        without a wave surface (grpc bidi) or heterogeneous lanes;
        retries and resyncs always ride the single-request wire."""
        out: dict[int, tuple] = {}
        by_client: dict[int, list[int]] = {}
        for lane in lanes:
            by_client.setdefault(self._lane_client[lane], []).append(lane)
        for ci, group in by_client.items():
            client = self._clients[ci]
            shapes = {(obs_list[lane].shape, str(obs_list[lane].dtype))
                      for lane in group}
            if (len(group) < 2 or not hasattr(client, "submit_wave")
                    or len(shapes) != 1
                    or any(mask_list[lane] is not None for lane in group)):
                for lane in group:
                    out[lane] = self._submit_lane(
                        lane, obs_list[lane], mask_list[lane],
                        with_win=False)
                continue
            entries, req_ids = [], []
            for lane in group:
                self._req_counter += 1
                req_ids.append(self._req_counter)
                entries.append({
                    "id": self._sids[lane], "req": self._req_counter,
                    "key": self._keys[lane], "obs": obs_list[lane],
                    "mask": None, "sid": self._sids[lane],
                    "stp": self._session_steps[lane] + 1,
                    "rst": self._episode_starts[lane]})
            waiters = client.submit_wave(pack_infer_wave(entries), req_ids)
            for lane, waiter, req_id in zip(group, waiters, req_ids):
                out[lane] = (waiter, req_id)
        return out

    def _apply_reply(self, lane: int, obs, reply: dict) -> str:
        from relayrl_tpu.transport.base import NACK_SESSION_EVICTED

        code = reply["code"]
        if code == NACK_SESSION_EVICTED:
            self._m_resyncs.inc()
            return "resync"
        if code == NACK_OVERLOADED:
            self._m_nacked.inc()
            return "overloaded"
        if code == NACK_UNAVAILABLE:
            raise RuntimeError(f"inference unavailable: {reply['error']}")
        if code != NACK_OK or "act" not in reply:
            return "overloaded"  # code-0 error: retryable
        self._keys[lane] = np.frombuffer(
            reply["key"], dtype=self._keys[lane].dtype).copy()
        self.version = reply["ver"]
        self._session_steps[lane] += 1
        self._episode_starts[lane] = False
        ctx = reply.get("ctx")
        if ctx is None:
            self._mirrors[lane] = None
        elif self._mirrors[lane] is not None:
            self._mirrors[lane].append(obs)
            if len(self._mirrors[lane]) > ctx:
                del self._mirrors[lane][:len(self._mirrors[lane]) - ctx]
        return "ok"

    @property
    def model_version(self) -> int:
        return self.version

    def _require_active(self) -> None:
        if not self.active or not self._clients:
            raise RuntimeError(
                "multiplexed remote client is not active "
                "(call enable_agent())")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.disable_agent()


class StandaloneInferenceHost:
    """An InferenceService on dedicated devices: subscribes to the model
    plane over any agent transport exactly like an actor (handshake →
    wire-v2 deltas → shared swap gate) and serves the zmq ROUTER action
    plane. The Sebulba "dedicated inference devices" placement; the
    colocated placement lives inside TrainingServer (zero wire hops).
    """

    def __init__(self, config_path: str | None = None,
                 server_type: str = "zmq", serving_addr: str | None = None,
                 handshake_timeout_s: float = 60.0, start: bool = True,
                 **addr_overrides):
        from relayrl_tpu.config import ConfigLoader
        from relayrl_tpu.transport import make_agent_transport

        self.config = ConfigLoader(None, config_path)
        from relayrl_tpu import telemetry

        telemetry.configure_from_config(self.config)
        self.transport = make_agent_transport(server_type, self.config,
                                              **addr_overrides)
        version, bundle_bytes = self.transport.fetch_model(
            handshake_timeout_s)
        bundle = ModelBundle.from_bytes(
            bundle_bytes, params_template=ModelBundle.RAW_TREE)
        bundle.version = version
        self.service = InferenceService.from_config(bundle, self.config)
        self.service.bind_zmq(
            serving_addr or self.config.get_inference_server().address)
        self.transport.on_model = self._on_model
        self.active = False
        if start:
            self.start()

    def _on_model(self, version: int, blob: bytes) -> None:
        from relayrl_tpu.transport.modelwire import WireBaseMismatch

        try:
            self.service.swap_from_wire(version, blob)
        except WireBaseMismatch:
            self.transport.request_resync()
        except Exception as e:
            print(f"[StandaloneInferenceHost] rejected model update: "
                  f"{e!r}", flush=True)

    def start(self) -> None:
        if self.active:
            return
        self.service.start()
        self.transport.start_model_listener()
        self.active = True

    def stop(self) -> None:
        if not self.active:
            return
        self.service.stop()
        self.transport.close()
        self.active = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


__all__ = ["InferenceService", "InferRequest", "RemoteActorClient",
           "MultiplexedRemoteClient", "StandaloneInferenceHost",
           "default_buckets", "CLOSE_SIZE", "CLOSE_DEADLINE"]
