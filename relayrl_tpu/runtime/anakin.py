"""Fused on-device rollout engine — the Anakin tier of the actor plane.

The vector actor host (``runtime/vector_actor.py``) batched the POLICY:
one ``jit(vmap(step))`` dispatch serves N env lanes. But each lane's env
still steps on the host, one Python call per step, so the system pays one
device dispatch + one numpy env loop + one ActionRecord build *per env
step* — ~30k env-steps/s end to end. The Podracer Anakin architecture
(arXiv:2104.06272) fuses the other half: with env dynamics as pure JAX
(``envs/jax/``), an entire ``[lanes, unroll]`` trajectory window becomes
ONE dispatch of

    jit(vmap_over_lanes(lax.scan(env.step ∘ policy.step)))

with per-lane PRNG keys split from one seed, in-scan autoreset
(``envs.jax.base.step_autoreset`` — lanes never leave the device between
episodes), and the whole carry (keys + env states + observations) donated
back to the next window. Amortized per env step, the dispatch cost tends
to zero as ``unroll_length`` grows; the scaling curve lives in
``benches/bench_anakin.py`` and the committed results row.

The host side of the engine is an **unstacker**: one ``device_get`` of
the stacked window, then a replay of the window into the existing
per-lane :class:`~relayrl_tpu.types.trajectory.Trajectory` streams —
byte-compatible with what a live ``VectorActorHost`` loop would have put
on the wire (reward-credit placement, terminal markers,
terminated-beats-truncated precedence, time-limit bootstrap
observations), so the spool/sequence/transport plane and the server's
ingest funnel work unchanged. This is a new fastest tier, not a
replacement: the gym/vector paths remain for host-bound envs
(Gymnasium, Atari) and external simulators.

Model hot-swap shares the exact gates of the other two actor hosts
(``apply_bundle_swap`` / ``apply_wire_swap`` — same attribute contract),
and the fused step reads ``params`` once per window under the lock: every
step of a window is computed by ONE model version by construction.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from relayrl_tpu.envs.jax.base import JaxEnv, step_autoreset
from relayrl_tpu.models import build_policy, validate_policy
from relayrl_tpu.runtime.policy_actor import (
    apply_bundle_swap,
    apply_wire_swap,
    resolve_actor_context,
    window_advance,
)
from relayrl_tpu.types.action import ActionRecord
from relayrl_tpu.types.columnar import (
    DecodedTrajectory,
    encode_columnar_frame,
)
from relayrl_tpu.types.model_bundle import ModelBundle, exploration_kwargs
from relayrl_tpu.types.trajectory import Trajectory


def resolve_jax_env(env, **env_kwargs) -> JaxEnv:
    """Env argument → :class:`JaxEnv` instance: ids go through the
    on-device registry (``envs.jax.JAX_ENVS`` — the same table
    ``envs.list_envs()`` reports), instances pass through."""
    if isinstance(env, JaxEnv):
        return env
    from relayrl_tpu.envs.jax import make_jax

    return make_jax(str(env), **env_kwargs)


def make_fused_rollout(policy, env: JaxEnv, unroll_length: int,
                       sequence: bool = False):
    """Build the one-dispatch window producer:

    ``fn(params, explore, carry) -> (carry, window)`` where ``carry`` is
    the stacked per-lane ``(policy_key, env_key, env_state, obs)`` and
    ``window`` is a dict of ``[lanes, unroll, ...]`` arrays (obs, act,
    rew, term, trunc, final_obs, aux). The policy composition per step is
    exactly the vector host's (``split`` inside the trace, params
    broadcast, exploration knobs as traced scalars so annealing never
    retraces); the env composition is :func:`step_autoreset`, so episode
    boundaries stay on-device. The carry is donated on accelerator
    backends — the window producer is a ring, not an allocator.

    ``sequence=True`` runs sequence policies: the carry grows a per-lane
    rolling observation window (``[W, obs_dim]`` ring + valid-length
    counter, advanced by :func:`window_advance` — the same rule every
    host tier pushes with) and each step dispatches through
    ``policy.step_window`` with the post-push count of real rows, so the
    action stream is bit-identical to a vector-tier ``step_window`` lane
    at the same key. The window resets to empty at in-scan autoreset
    boundaries via the same ``jnp.where`` masking ``step_autoreset``
    uses for the env state — a new episode never attends the previous
    one's tail. Shipped obs follow ``normalize_obs``'s wire-dtype rule
    (uint8 stays uint8, everything else float32) because the vector
    tier normalizes BEFORE windowing, and byte parity rides on it.
    The window recomputes attention from the ring each step — the
    KV-cache (``step_cached``) stays off the scan path: a cache carry
    would be ``[W, n_layers, n_heads, ...]`` per lane and its positions
    shift on every roll, which re-materializes the whole cache anyway.
    """
    def lane_rollout(params, explore, carry):
        def seq_body(c, _):
            pkey, ekey, state, obs, win, wlen = c
            pkey, sub = jax.random.split(pkey)
            wire_obs = (obs if obs.dtype == jnp.uint8
                        else jnp.asarray(obs, jnp.float32))
            win, wlen = window_advance(win, wlen, wire_obs)
            # step_window takes the post-push count of REAL rows (it
            # reads out at t-1 itself) — same convention as the hosts.
            act, aux = policy.step_window(params, sub, win, wlen, None)
            (ekey, state, next_obs, rew, term, trunc,
             final_obs) = step_autoreset(env, ekey, state, act)
            done = jnp.logical_or(term, trunc)
            win = jnp.where(done, jnp.zeros_like(win), win)
            wlen = jnp.where(done, jnp.int32(0), wlen)
            out = {"obs": wire_obs, "act": act, "rew": rew, "term": term,
                   "trunc": trunc, "final_obs": final_obs, "aux": aux}
            return (pkey, ekey, state, next_obs, win, wlen), out

        def body(c, _):
            pkey, ekey, state, obs = c
            pkey, sub = jax.random.split(pkey)
            act, aux = policy.step(params, sub, obs, None, **explore)
            (ekey, state, next_obs, rew, term, trunc,
             final_obs) = step_autoreset(env, ekey, state, act)
            out = {"obs": obs, "act": act, "rew": rew, "term": term,
                   "trunc": trunc, "final_obs": final_obs, "aux": aux}
            return (pkey, ekey, state, next_obs), out

        return jax.lax.scan(seq_body if sequence else body, carry, None,
                            length=unroll_length)

    vect = jax.vmap(lane_rollout, in_axes=(None, None, 0))
    # Donation is honored on TPU/GPU; CPU hosts would warn per dispatch.
    donate = (2,) if jax.default_backend() != "cpu" else ()
    return jax.jit(vect, donate_argnums=donate)


class AnakinActorHost:
    """N on-device env lanes × ``unroll_length`` steps per fused dispatch.

    Same logical-agent surface as :class:`VectorActorHost` — N per-lane
    trajectory streams through ``on_send(lane, payload)``, one atomic
    model gate for all lanes — but the action API is :meth:`rollout`:
    there is no per-step request because the env lives inside the
    dispatch. ``rng_keys`` (stacked ``[N, 2]``) overrides the default
    per-lane policy-key derivation, mirroring VectorActorHost's parity
    hook.

    Sequence policies (windowed transformers) run fused too: the scan
    carry holds each lane's rolling observation window, ``window_size``
    optionally narrows it below the model context (clamped exactly like
    ``actor.window_size`` on the other tiers), and ``record_bver=True``
    stamps each record's producing model version into the aux plane —
    the per-token behavior evidence the RLHF score stage reads.
    """

    def __init__(
        self,
        bundle: ModelBundle,
        env,
        num_envs: int,
        unroll_length: int = 32,
        max_traj_length: int = 1000,
        on_send=None,
        seed: int = 0,
        validate: bool = True,
        rng_keys=None,
        columnar_wire: bool = True,
        async_emit: bool = False,
        emit_coalesce_frames: int = 1,
        window_size: int | None = None,
        record_bver: bool = False,
        **env_kwargs,
    ):
        if num_envs < 1:
            raise ValueError(f"num_envs must be >= 1, got {num_envs}")
        if unroll_length < 1:
            raise ValueError(
                f"unroll_length must be >= 1, got {unroll_length}")
        self._lock = threading.Lock()
        self.num_envs = int(num_envs)
        self.unroll_length = int(unroll_length)
        self.env = resolve_jax_env(env, **env_kwargs)
        self.arch = dict(bundle.arch)
        obs_dim = int(self.arch["obs_dim"])
        if obs_dim != self.env.obs_dim:
            raise ValueError(
                f"model obs_dim {obs_dim} != env obs_dim "
                f"{self.env.obs_dim} — the fused rollout feeds the env's "
                f"observation straight into the policy")
        self.policy = build_policy(self.arch)
        if validate:
            validate_policy(self.policy, bundle.params)
        # Sequence policies run fused: the scan carry grows a per-lane
        # rolling window sized to the model's serving context (narrowed
        # by actor.window_size when set — never widened past it, the
        # same clamp resolve_actor_context applies on the other tiers).
        self._window_size: int | None = None
        if self.policy.step_window is not None:
            ctx = resolve_actor_context(self.arch)
            self._window_size = (ctx if window_size is None
                                 else max(1, min(int(window_size), ctx)))
        elif getattr(self.policy, "step_cached", None) is not None:
            raise ValueError(
                "KV-cache-only policies (step_cached without step_window) "
                "cannot run in the fused scan — the cache carry's "
                "positions shift on every window roll, so the scan "
                "recomputes from the rolling window instead; use "
                "actor.host_mode=\"process\" for the cached single-lane "
                "path or the serving plane (InferenceService) for "
                "stateless clients")
        self.params = bundle.params
        self.version = bundle.version
        self._explore_kwargs = exploration_kwargs(self.arch)
        self._wire_decoder = None  # one decoder, all lanes (see VectorActorHost)
        # Per-token behavior evidence for the RLHF plane: stamp each
        # record's producing model version (``bver``) into the window's
        # aux at unstack. Opt-in — it widens the wire by one int32
        # column, so plain RL rollouts keep their bytes.
        self.record_bver = bool(record_bver)
        self._rollout_fn = make_fused_rollout(
            self.policy, self.env, self.unroll_length,
            sequence=self._window_size is not None)

        # Per-lane key derivation matches VectorActorHost (policy keys
        # split from PRNGKey(seed)); env reset/autoreset keys come from an
        # independent fold so policy and env streams never alias.
        if rng_keys is not None:
            keys = jnp.asarray(np.asarray(rng_keys))
            if keys.shape[0] != self.num_envs:
                raise ValueError(
                    f"rng_keys has {keys.shape[0]} rows for "
                    f"{self.num_envs} lanes")
            pol_keys = keys
        else:
            pol_keys = jax.random.split(
                jax.random.PRNGKey(seed), self.num_envs)
        env_root = jax.random.fold_in(jax.random.PRNGKey(seed), 0x0E74)
        reset_keys = jax.random.split(env_root, 2 * self.num_envs)
        init_keys, carry_keys = (reset_keys[: self.num_envs],
                                 reset_keys[self.num_envs:])
        states, obs = jax.jit(jax.vmap(self.env.reset))(init_keys)
        if self._window_size is not None:
            # Windows are ALWAYS float32, matching both host tiers —
            # the push casts, the wire obs keeps normalize_obs's dtype.
            win = jnp.zeros(
                (self.num_envs, self._window_size, int(self.env.obs_dim)),
                jnp.float32)
            wlen = jnp.zeros(self.num_envs, jnp.int32)
            self._carry = (pol_keys, carry_keys, states, obs, win, wlen)
        else:
            self._carry = (pol_keys, carry_keys, states, obs)

        # Wire form: ``columnar_wire=True`` (the anakin-tier default,
        # config ``actor.columnar_wire``) ships each completed per-lane
        # segment as ONE contiguous columnar frame (types/columnar.py)
        # sliced straight out of the host-resident window — zero per-step
        # Python objects, zero per-record msgpack. False keeps the
        # per-record ActionRecord streams (rolling compat / pre-columnar
        # servers), now unstacked with O(episodes) boundary slicing.
        self.columnar_wire = bool(columnar_wire)
        self.max_traj_length = int(max_traj_length)
        self._on_send = on_send
        # actor.emit_coalesce_frames (ROADMAP item 5 host shave): pack
        # up to N completed columnar segments of one lane into a single
        # batch-container send (transport/base.pack_batch) — short
        # episodes complete several segments per window, and each send
        # pays the envelope + spool + socket path. Flushed at window
        # end, so a frame never waits past its own rollout dispatch.
        # Only meaningful on the columnar wire (per-record payloads are
        # already per-episode msgpack).
        self.emit_coalesce = max(1, int(emit_coalesce_frames))
        self._coalesce_buf: list[list[bytes]] = [
            [] for _ in range(self.num_envs)]
        # Tracing stamps (telemetry/trace.py): the window production
        # stamp (rollout dispatch start) plus the last frame's encode
        # bracket, read by VectorAgent._emit_stamps when it mints a
        # trajectory trace context for an emitted columnar segment.
        self._window_born_ns = 0
        self._last_emit_stamps: tuple[int, int, int] | None = None
        self.trajectories = [
            Trajectory(
                max_length=max_traj_length,
                on_send=(None if on_send is None
                         else (lambda payload, _lane=lane:
                               on_send(_lane, payload))))
            for lane in range(self.num_envs)
        ]
        # Per-lane columnar accumulators: column chunks (window slices)
        # pending until an episode boundary / max_traj_length flush.
        self._pending = [
            {"len": 0, "cols": {"o": [], "a": [], "r": []}, "aux": {}}
            for _ in range(self.num_envs)]
        # Per-lane episode accounting (drivers read these like
        # run_vector_gym_loop's return value).
        self._ep_ret = np.zeros(self.num_envs, np.float64)
        self.episode_returns: list[list[float]] = [
            [] for _ in range(self.num_envs)]

        # Off-thread emitter (ROADMAP item 1's host shave): with
        # host_share_of_wall at 0.43-0.55, frame encode is ~coequal with
        # device dispatch — ``async_emit=True`` (config
        # ``actor.async_emit``) moves the encode/unstack + send off the
        # rollout thread onto a dedicated emitter, overlapping it with
        # the NEXT window's device compute. The hand-off queue is
        # bounded (depth 2): a slow wire backpressures the rollout loop
        # instead of ballooning host memory, and one emitter thread
        # keeps per-lane trajectory order intact. ``flush_emits`` drains
        # it (drivers call it before reading episode_returns or tearing
        # down).
        self.async_emit = bool(async_emit)
        self._emit_cond = threading.Condition()
        self._emit_queue: list[dict] = []
        self._emit_pending = 0
        self._emit_error: Exception | None = None
        self._emit_stop = False
        self._emit_thread: threading.Thread | None = None
        self.start_emitter()

        from relayrl_tpu import telemetry

        reg = telemetry.get_registry()
        self._m_steps = reg.counter(
            "relayrl_actor_env_steps_total",
            "policy steps served (one per env step per lane)")
        self._m_dispatches = reg.counter(
            "relayrl_actor_rollout_dispatches_total",
            "fused rollout dispatches (each serves lanes x unroll steps)")
        self._m_dispatch_s = reg.histogram(
            "relayrl_actor_rollout_dispatch_seconds",
            "fused rollout: device compute per [lanes, unroll] window")
        self._m_unstack_s = reg.histogram(
            "relayrl_actor_rollout_unstack_seconds",
            "fused rollout: host unstack of one window into trajectories")
        self._m_encode_s = reg.histogram(
            "relayrl_actor_rollout_encode_seconds",
            "fused rollout: columnar frame encode of one window")
        self._m_frames = reg.counter(
            "relayrl_actor_columnar_frames_total",
            "columnar trajectory frames encoded and handed to the wire")
        self._m_frame_bytes = reg.counter(
            "relayrl_actor_columnar_bytes_total",
            "columnar trajectory frame bytes encoded")
        self._m_sends = reg.counter(
            "relayrl_actor_emit_sends_total",
            "transport sends of encoded segments (emit_coalesce_frames "
            "folds several frames into one send)")
        reg.gauge("relayrl_actor_lanes",
                  "env lanes per batched dispatch on this host").set(
                      self.num_envs)
        reg.gauge("relayrl_actor_unroll_length",
                  "env steps per lane per fused rollout dispatch").set(
                      self.unroll_length)
        if self._window_size is not None:
            reg.gauge(
                "relayrl_actor_window_size",
                "rolling observation-window rows per lane in the fused "
                "sequence scan carry (0 rows = feed-forward policy)"
            ).set(self._window_size)

    # -- fused action API --
    def rollout(self) -> dict:
        """ONE device dispatch producing ``lanes × unroll`` env steps,
        then the host unstack into the per-lane trajectory streams.

        Returns ``{"steps", "episodes", "dispatch_s", "unstack_s"}`` for
        the calling driver's accounting; completed episode returns
        accumulate on :attr:`episode_returns` per lane.
        """
        t0 = time.monotonic()
        born_ns = time.monotonic_ns()
        with self._lock:
            # ONE params/explore read under the lock for the whole
            # window: every step of this window is computed by a single
            # model version (maybe_swap's atomicity across lanes AND
            # unroll steps).
            version = self.version
            self._carry, window = self._rollout_fn(
                self.params, self._explore_kwargs, self._carry)
        window = jax.block_until_ready(window)
        t1 = time.monotonic()
        host_window = jax.device_get(window)
        if self.record_bver:
            # The whole window is one model version by construction
            # (params read once under the lock), so the stamp is a fill.
            host_window = dict(host_window)
            host_window["aux"] = dict(host_window["aux"])
            host_window["aux"]["bver"] = np.full(
                (self.num_envs, self.unroll_length), version, np.int32)
        if self.async_emit:
            if self._emit_error is not None:
                err, self._emit_error = self._emit_error, None
                raise RuntimeError(
                    f"anakin emitter thread failed: {err!r}") from err
            with self._emit_cond:
                # Bounded hand-off: past depth 2 the rollout thread
                # waits — backpressure, not unbounded window buffering.
                while self._emit_pending >= 2 and not self._emit_stop:
                    self._emit_cond.wait(0.5)
                self._emit_queue.append((born_ns, host_window))
                self._emit_pending += 1
                self._emit_cond.notify_all()
            episodes = 0  # completed counts surface via episode_returns
        elif self.columnar_wire:
            self._window_born_ns = born_ns
            episodes = self._emit_columnar(host_window)
        else:
            self._window_born_ns = born_ns
            episodes = self._unstack(host_window)
        t2 = time.monotonic()
        steps = self.num_envs * self.unroll_length
        self._m_steps.inc(steps)
        self._m_dispatches.inc()
        self._m_dispatch_s.observe(t1 - t0)
        if not self.async_emit:
            if self.columnar_wire:
                self._m_encode_s.observe(t2 - t1)
            else:
                self._m_unstack_s.observe(t2 - t1)
        return {"steps": steps, "episodes": episodes,
                "dispatch_s": t1 - t0, "unstack_s": t2 - t1,
                "encode_s": t2 - t1 if self.columnar_wire else 0.0,
                "wire": "columnar" if self.columnar_wire else "records"}

    # -- off-thread emitter (async_emit=True) --
    def start_emitter(self) -> None:
        """(Re)start the async emitter thread — a no-op when
        ``async_emit`` is off or it is already running. The re-enable
        half of :meth:`close`: an agent cycling disable/enable must get
        a live emitter back, or the depth-2 hand-off would deadlock on
        the third window."""
        if not self.async_emit or self._emit_thread is not None:
            return
        self._emit_stop = False
        self._emit_thread = threading.Thread(
            target=self._emit_loop, name="anakin-emitter", daemon=True)
        self._emit_thread.start()

    def _emit_loop(self) -> None:
        while True:
            with self._emit_cond:
                while not self._emit_queue and not self._emit_stop:
                    self._emit_cond.wait(0.5)
                if self._emit_stop and not self._emit_queue:
                    return
                born_ns, w = self._emit_queue.pop(0)
            self._window_born_ns = born_ns  # single emitter thread
            t0 = time.monotonic()
            try:
                if self.columnar_wire:
                    self._emit_columnar(w)
                    self._m_encode_s.observe(time.monotonic() - t0)
                else:
                    self._unstack(w)
                    self._m_unstack_s.observe(time.monotonic() - t0)
            except Exception as e:
                # Surfaced on the NEXT rollout() — the emitter must not
                # die silently with windows still queuing behind it.
                self._emit_error = e
            finally:
                with self._emit_cond:
                    self._emit_pending -= 1
                    self._emit_cond.notify_all()

    def flush_emits(self, timeout_s: float = 30.0) -> bool:
        """Drain the async emitter's hand-off queue (no-op when
        ``async_emit`` is off): drivers call this before reading
        ``episode_returns`` or tearing down, so every dispatched window
        has reached the wire. True when fully drained in time. A
        pending emit failure re-raises HERE too, not only on the next
        rollout — otherwise an error on the FINAL window (no next
        rollout coming) would silently lose it at teardown, where the
        sync path would have raised."""
        if not self.async_emit:
            return True
        deadline = time.monotonic() + timeout_s
        drained = True
        with self._emit_cond:
            while self._emit_pending > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    drained = False
                    break
                self._emit_cond.wait(min(0.5, remaining))
        if self._emit_error is not None:
            err, self._emit_error = self._emit_error, None
            raise RuntimeError(
                f"anakin emitter thread failed: {err!r}") from err
        return drained

    def close(self, timeout_s: float = 30.0) -> None:
        """Stop the emitter thread after draining its queue (hosts
        without ``async_emit`` have nothing to do)."""
        if self._emit_thread is None:
            return
        self.flush_emits(timeout_s)
        with self._emit_cond:
            self._emit_stop = True
            self._emit_cond.notify_all()
        self._emit_thread.join(timeout=5)
        self._emit_thread = None

    @staticmethod
    def _cat(chunks: list) -> np.ndarray:
        return chunks[0] if len(chunks) == 1 else np.concatenate(chunks)

    def _emit_columnar(self, w: dict) -> int:
        """Columnar wire: slice each completed per-lane segment out of
        the host-resident ``[lanes, unroll]`` window and ship it as one
        contiguous frame (types/columnar.py), already in the FOLDED form
        the server's native decoder produces from the per-record wire:
        the final step carries its full reward (``r``), ``t``/``x`` mark
        the terminal (terminated beats truncated), ``u`` mirrors
        ``reward_updated`` (zero on the terminal step, whose reward
        "rides the marker" — ``n_records`` counts it), and a pure
        time-limit ending ships the pre-reset observation as
        ``final_obs``. Episode-boundary detection is one vectorized
        pass; the only per-episode Python is the frame flush."""
        term, trunc = w["term"], w["trunc"]
        done = np.logical_or(term, trunc)
        episodes = 0
        for lane in range(self.num_envs):
            start = 0
            for b in np.flatnonzero(done[lane]).tolist():
                self._append_segment(lane, w, start, b + 1)
                terminated = bool(term[lane, b])
                self._flush_frame(
                    lane, ended=True, truncated=not terminated,
                    final=(None if terminated else
                           np.asarray(w["final_obs"][lane, b], np.float32)))
                episodes += 1
                start = b + 1
            if start < self.unroll_length:
                self._append_segment(lane, w, start, self.unroll_length)
        if self.emit_coalesce > 1:
            # Window-end flush: coalescing trades sends for latency
            # bounded by ONE window, never more.
            for lane in range(self.num_envs):
                self._flush_coalesced(lane)
        return episodes

    def _flush_coalesced(self, lane: int) -> None:
        """Ship the lane's pending frames as one send: a single frame
        goes verbatim (the server's columnar sniff path), several pack
        into a BATCH_KIND_FRAMES container (split + decoded per frame
        by a staging worker). Either way it is ONE spool entry — one
        seq, one envelope — so replay/dedup act on the whole group."""
        buf = self._coalesce_buf[lane]
        if not buf:
            return
        if len(buf) == 1:
            payload = buf[0]
        else:
            from relayrl_tpu.transport.base import (
                BATCH_KIND_FRAMES,
                pack_batch,
            )

            payload = pack_batch(BATCH_KIND_FRAMES, buf)
        buf.clear()
        if self._on_send is not None:
            self._m_sends.inc()
            self._on_send(lane, payload)

    def _append_segment(self, lane: int, w: dict, a: int, b: int) -> None:
        """Stash window slice ``[a, b)`` on the lane's pending columns,
        flushing max_traj_length-sized chunks exactly where the
        per-record path would (Trajectory.add_action flushes when a real
        step arrives at capacity, so chunks are exactly max_traj_length
        steps and the terminal marker always joins its chunk)."""
        p = self._pending[lane]
        cols, aux_p = p["cols"], p["aux"]
        while a < b:
            if p["len"] >= self.max_traj_length:
                self._flush_frame(lane, ended=False)
            stop = min(b, a + self.max_traj_length - p["len"])
            cols["o"].append(w["obs"][lane, a:stop])
            cols["a"].append(w["act"][lane, a:stop])
            cols["r"].append(w["rew"][lane, a:stop])
            for k, v in w["aux"].items():
                aux_p.setdefault(k, []).append(v[lane, a:stop])
            p["len"] += stop - a
            self._ep_ret[lane] += float(
                np.sum(w["rew"][lane, a:stop], dtype=np.float64))
            a = stop

    def _flush_frame(self, lane: int, ended: bool, truncated: bool = False,
                     final=None) -> None:
        p = self._pending[lane]
        n = p["len"]
        if n == 0:
            return
        r = self._cat(p["cols"]["r"])
        t_col = np.zeros(n, np.uint8)
        x_col = np.zeros(n, np.uint8)
        u_col = (r != 0.0).astype(np.uint8)
        if ended:
            t_col[-1] = 1
            u_col[-1] = 0
            if truncated:
                x_col[-1] = 1
        time_limited = bool(ended and truncated)
        dt = DecodedTrajectory(
            agent_id="",  # attribution rides the transport envelope
            n_steps=n, n_records=n + (1 if ended else 0),
            marker_truncated=time_limited,
            columns={"o": self._cat(p["cols"]["o"]),
                     "a": self._cat(p["cols"]["a"]),
                     "r": r, "t": t_col, "u": u_col, "x": x_col},
            aux={k: self._cat(chunks) for k, chunks in p["aux"].items()},
            final_obs=final if time_limited else None)
        from relayrl_tpu.telemetry import trace as trace_mod

        if trace_mod.get_tracer().enabled:
            enc0 = time.monotonic_ns()
            frame = encode_columnar_frame(dt)
            self._last_emit_stamps = (self._window_born_ns or enc0,
                                      enc0, time.monotonic_ns())
        else:
            frame = encode_columnar_frame(dt)
        self._m_frames.inc()
        self._m_frame_bytes.inc(len(frame))
        if self.emit_coalesce > 1:
            buf = self._coalesce_buf[lane]
            buf.append(frame)
            if len(buf) >= self.emit_coalesce:
                self._flush_coalesced(lane)
        elif self._on_send is not None:
            self._m_sends.inc()
            self._on_send(lane, frame)
        if ended:
            self.episode_returns[lane].append(float(self._ep_ret[lane]))
            self._ep_ret[lane] = 0.0
        p["len"] = 0
        for chunks in p["cols"].values():
            chunks.clear()
        for chunks in p["aux"].values():
            chunks.clear()

    def _unstack(self, w: dict) -> int:
        """Per-record fallback (``columnar_wire=False``): replay one
        host-side window into the per-lane trajectories, reproducing the
        live loop's wire shape exactly: reward r_t lands on the record of
        the action that EARNED it (``reward_updated`` set only for
        nonzero rewards, as ``update_reward`` would have), the final
        action of an episode keeps rew=0 with its reward riding the
        terminal marker (``flag_last_action`` semantics), terminated
        beats truncated, and a pure time-limit ending ships the
        pre-reset observation for the value bootstrap.

        Episode boundaries come from one vectorized pass
        (``np.flatnonzero(term | trunc)``), scalars bulk-convert via
        ``tolist``, and records land through the bulk
        ``Trajectory.add_actions`` — O(episodes) loop control instead of
        the old per-step ``add_action`` calls."""
        obs, act, rew = w["obs"], w["act"], w["rew"]
        term, trunc, final_obs = w["term"], w["trunc"], w["final_obs"]
        aux_items = list(w["aux"].items())
        done = np.logical_or(term, trunc)
        episodes = 0
        for lane in range(self.num_envs):
            traj = self.trajectories[lane]
            obs_l, act_l = obs[lane], act[lane]
            rew_l = rew[lane].tolist()
            aux_l = [(k, v[lane]) for k, v in aux_items]

            def seg_records(a, b, last_masked, _obs_l=obs_l, _act_l=act_l,
                            _rew_l=rew_l, _aux_l=aux_l):
                # last_masked: index whose record keeps rew=0 (the
                # terminal step — its reward rides the marker), -1 for
                # an unterminated trailing segment.
                return [ActionRecord(
                    obs=_obs_l[t],
                    act=np.asarray(_act_l[t]),
                    mask=None,
                    rew=0.0 if t == last_masked else _rew_l[t],
                    reward_updated=bool(t != last_masked
                                        and _rew_l[t] != 0.0),
                    data={k: np.asarray(v[t]) for k, v in _aux_l},
                    done=False,
                ) for t in range(a, b)]

            start = 0
            for b in np.flatnonzero(done[lane]).tolist():
                records = seg_records(start, b + 1, last_masked=b)
                terminated = bool(term[lane, b])
                time_limited = not terminated
                records.append(ActionRecord(
                    obs=(np.asarray(final_obs[lane, b], np.float32)
                         if time_limited else None),
                    rew=rew_l[b], done=True, truncated=time_limited))
                traj.add_actions(records)
                self._ep_ret[lane] += float(
                    np.sum(rew[lane, start:b + 1], dtype=np.float64))
                self.episode_returns[lane].append(float(self._ep_ret[lane]))
                self._ep_ret[lane] = 0.0
                episodes += 1
                start = b + 1
            if start < self.unroll_length:
                traj.add_actions(seg_records(start, self.unroll_length,
                                             last_masked=-1))
                self._ep_ret[lane] += float(
                    np.sum(rew[lane, start:], dtype=np.float64))
        return episodes

    # -- model hot-swap (one gate, all lanes, whole windows) --
    def maybe_swap(self, bundle: ModelBundle) -> bool:
        """Install a newer model for every lane atomically; a window in
        flight finishes on the old version, the next reads the new one
        (shared gate with PolicyActor/VectorActorHost)."""
        return apply_bundle_swap(self, bundle)

    def swap_from_bytes(self, buf: bytes) -> bool:
        return self.maybe_swap(
            ModelBundle.from_bytes(buf, params_template=ModelBundle.RAW_TREE))

    def swap_from_wire(self, version: int, blob: bytes):
        """Wire-v2-aware swap shared with the other actor hosts."""
        return apply_wire_swap(self, version, blob)


def run_anakin_loop(host, windows: int) -> list[list[float]]:
    """Drive ``windows`` fused dispatches through an
    :class:`AnakinActorHost` (or the networked anakin-mode
    ``VectorAgent`` — same ``rollout()`` surface). Returns per-lane
    completed episode returns, mirroring ``run_vector_gym_loop``."""
    for _ in range(windows):
        host.rollout()
    returns = getattr(host, "episode_returns", None)
    if returns is None:  # networked facade: reach through to the host
        returns = host.host.episode_returns
    return [list(r) for r in returns]
