"""Fused on-device rollout engine — the Anakin tier of the actor plane.

The vector actor host (``runtime/vector_actor.py``) batched the POLICY:
one ``jit(vmap(step))`` dispatch serves N env lanes. But each lane's env
still steps on the host, one Python call per step, so the system pays one
device dispatch + one numpy env loop + one ActionRecord build *per env
step* — ~30k env-steps/s end to end. The Podracer Anakin architecture
(arXiv:2104.06272) fuses the other half: with env dynamics as pure JAX
(``envs/jax/``), an entire ``[lanes, unroll]`` trajectory window becomes
ONE dispatch of

    jit(vmap_over_lanes(lax.scan(env.step ∘ policy.step)))

with per-lane PRNG keys split from one seed, in-scan autoreset
(``envs.jax.base.step_autoreset`` — lanes never leave the device between
episodes), and the whole carry (keys + env states + observations) donated
back to the next window. Amortized per env step, the dispatch cost tends
to zero as ``unroll_length`` grows; the scaling curve lives in
``benches/bench_anakin.py`` and the committed results row.

The host side of the engine is an **unstacker**: one ``device_get`` of
the stacked window, then a replay of the window into the existing
per-lane :class:`~relayrl_tpu.types.trajectory.Trajectory` streams —
byte-compatible with what a live ``VectorActorHost`` loop would have put
on the wire (reward-credit placement, terminal markers,
terminated-beats-truncated precedence, time-limit bootstrap
observations), so the spool/sequence/transport plane and the server's
ingest funnel work unchanged. This is a new fastest tier, not a
replacement: the gym/vector paths remain for host-bound envs
(Gymnasium, Atari) and external simulators.

Model hot-swap shares the exact gates of the other two actor hosts
(``apply_bundle_swap`` / ``apply_wire_swap`` — same attribute contract),
and the fused step reads ``params`` once per window under the lock: every
step of a window is computed by ONE model version by construction.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from relayrl_tpu.envs.jax.base import JaxEnv, step_autoreset
from relayrl_tpu.models import build_policy, validate_policy
from relayrl_tpu.runtime.policy_actor import (
    apply_bundle_swap,
    apply_wire_swap,
)
from relayrl_tpu.types.action import ActionRecord
from relayrl_tpu.types.model_bundle import ModelBundle, exploration_kwargs
from relayrl_tpu.types.trajectory import Trajectory


def resolve_jax_env(env, **env_kwargs) -> JaxEnv:
    """Env argument → :class:`JaxEnv` instance: ids go through the
    on-device registry (``envs.jax.JAX_ENVS`` — the same table
    ``envs.list_envs()`` reports), instances pass through."""
    if isinstance(env, JaxEnv):
        return env
    from relayrl_tpu.envs.jax import make_jax

    return make_jax(str(env), **env_kwargs)


def make_fused_rollout(policy, env: JaxEnv, unroll_length: int):
    """Build the one-dispatch window producer:

    ``fn(params, explore, carry) -> (carry, window)`` where ``carry`` is
    the stacked per-lane ``(policy_key, env_key, env_state, obs)`` and
    ``window`` is a dict of ``[lanes, unroll, ...]`` arrays (obs, act,
    rew, term, trunc, final_obs, aux). The policy composition per step is
    exactly the vector host's (``split`` inside the trace, params
    broadcast, exploration knobs as traced scalars so annealing never
    retraces); the env composition is :func:`step_autoreset`, so episode
    boundaries stay on-device. The carry is donated on accelerator
    backends — the window producer is a ring, not an allocator.
    """
    def lane_rollout(params, explore, carry):
        def body(c, _):
            pkey, ekey, state, obs = c
            pkey, sub = jax.random.split(pkey)
            act, aux = policy.step(params, sub, obs, None, **explore)
            (ekey, state, next_obs, rew, term, trunc,
             final_obs) = step_autoreset(env, ekey, state, act)
            out = {"obs": obs, "act": act, "rew": rew, "term": term,
                   "trunc": trunc, "final_obs": final_obs, "aux": aux}
            return (pkey, ekey, state, next_obs), out

        return jax.lax.scan(body, carry, None, length=unroll_length)

    vect = jax.vmap(lane_rollout, in_axes=(None, None, 0))
    # Donation is honored on TPU/GPU; CPU hosts would warn per dispatch.
    donate = (2,) if jax.default_backend() != "cpu" else ()
    return jax.jit(vect, donate_argnums=donate)


class AnakinActorHost:
    """N on-device env lanes × ``unroll_length`` steps per fused dispatch.

    Same logical-agent surface as :class:`VectorActorHost` — N per-lane
    trajectory streams through ``on_send(lane, payload)``, one atomic
    model gate for all lanes — but the action API is :meth:`rollout`:
    there is no per-step request because the env lives inside the
    dispatch. ``rng_keys`` (stacked ``[N, 2]``) overrides the default
    per-lane policy-key derivation, mirroring VectorActorHost's parity
    hook.
    """

    def __init__(
        self,
        bundle: ModelBundle,
        env,
        num_envs: int,
        unroll_length: int = 32,
        max_traj_length: int = 1000,
        on_send=None,
        seed: int = 0,
        validate: bool = True,
        rng_keys=None,
        **env_kwargs,
    ):
        if num_envs < 1:
            raise ValueError(f"num_envs must be >= 1, got {num_envs}")
        if unroll_length < 1:
            raise ValueError(
                f"unroll_length must be >= 1, got {unroll_length}")
        self._lock = threading.Lock()
        self.num_envs = int(num_envs)
        self.unroll_length = int(unroll_length)
        self.env = resolve_jax_env(env, **env_kwargs)
        self.arch = dict(bundle.arch)
        obs_dim = int(self.arch["obs_dim"])
        if obs_dim != self.env.obs_dim:
            raise ValueError(
                f"model obs_dim {obs_dim} != env obs_dim "
                f"{self.env.obs_dim} — the fused rollout feeds the env's "
                f"observation straight into the policy")
        self.policy = build_policy(self.arch)
        if validate:
            validate_policy(self.policy, bundle.params)
        if self.policy.step_window is not None:
            raise ValueError(
                "sequence policies are not supported by the fused rollout "
                "engine yet (the scan carry would need the rolling window "
                "pytree); use actor.host_mode=\"vector\"")
        self.params = bundle.params
        self.version = bundle.version
        self._explore_kwargs = exploration_kwargs(self.arch)
        self._wire_decoder = None  # one decoder, all lanes (see VectorActorHost)
        self._rollout_fn = make_fused_rollout(
            self.policy, self.env, self.unroll_length)

        # Per-lane key derivation matches VectorActorHost (policy keys
        # split from PRNGKey(seed)); env reset/autoreset keys come from an
        # independent fold so policy and env streams never alias.
        if rng_keys is not None:
            keys = jnp.asarray(np.asarray(rng_keys))
            if keys.shape[0] != self.num_envs:
                raise ValueError(
                    f"rng_keys has {keys.shape[0]} rows for "
                    f"{self.num_envs} lanes")
            pol_keys = keys
        else:
            pol_keys = jax.random.split(
                jax.random.PRNGKey(seed), self.num_envs)
        env_root = jax.random.fold_in(jax.random.PRNGKey(seed), 0x0E74)
        reset_keys = jax.random.split(env_root, 2 * self.num_envs)
        init_keys, carry_keys = (reset_keys[: self.num_envs],
                                 reset_keys[self.num_envs:])
        states, obs = jax.jit(jax.vmap(self.env.reset))(init_keys)
        self._carry = (pol_keys, carry_keys, states, obs)

        self.trajectories = [
            Trajectory(
                max_length=max_traj_length,
                on_send=(None if on_send is None
                         else (lambda payload, _lane=lane:
                               on_send(_lane, payload))))
            for lane in range(self.num_envs)
        ]
        # Per-lane episode accounting (drivers read these like
        # run_vector_gym_loop's return value).
        self._ep_ret = np.zeros(self.num_envs, np.float64)
        self.episode_returns: list[list[float]] = [
            [] for _ in range(self.num_envs)]

        from relayrl_tpu import telemetry

        reg = telemetry.get_registry()
        self._m_steps = reg.counter(
            "relayrl_actor_env_steps_total",
            "policy steps served (one per env step per lane)")
        self._m_dispatches = reg.counter(
            "relayrl_actor_rollout_dispatches_total",
            "fused rollout dispatches (each serves lanes x unroll steps)")
        self._m_dispatch_s = reg.histogram(
            "relayrl_actor_rollout_dispatch_seconds",
            "fused rollout: device compute per [lanes, unroll] window")
        self._m_unstack_s = reg.histogram(
            "relayrl_actor_rollout_unstack_seconds",
            "fused rollout: host unstack of one window into trajectories")
        reg.gauge("relayrl_actor_lanes",
                  "env lanes per batched dispatch on this host").set(
                      self.num_envs)
        reg.gauge("relayrl_actor_unroll_length",
                  "env steps per lane per fused rollout dispatch").set(
                      self.unroll_length)

    # -- fused action API --
    def rollout(self) -> dict:
        """ONE device dispatch producing ``lanes × unroll`` env steps,
        then the host unstack into the per-lane trajectory streams.

        Returns ``{"steps", "episodes", "dispatch_s", "unstack_s"}`` for
        the calling driver's accounting; completed episode returns
        accumulate on :attr:`episode_returns` per lane.
        """
        t0 = time.monotonic()
        with self._lock:
            # ONE params/explore read under the lock for the whole
            # window: every step of this window is computed by a single
            # model version (maybe_swap's atomicity across lanes AND
            # unroll steps).
            self._carry, window = self._rollout_fn(
                self.params, self._explore_kwargs, self._carry)
        window = jax.block_until_ready(window)
        t1 = time.monotonic()
        host_window = jax.device_get(window)
        episodes = self._unstack(host_window)
        t2 = time.monotonic()
        steps = self.num_envs * self.unroll_length
        self._m_steps.inc(steps)
        self._m_dispatches.inc()
        self._m_dispatch_s.observe(t1 - t0)
        self._m_unstack_s.observe(t2 - t1)
        return {"steps": steps, "episodes": episodes,
                "dispatch_s": t1 - t0, "unstack_s": t2 - t1}

    def _unstack(self, w: dict) -> int:
        """Replay one host-side window into the per-lane trajectories,
        reproducing the live loop's wire shape exactly: reward r_t lands
        on the record of the action that EARNED it (``reward_updated``
        set only for nonzero rewards, as ``update_reward`` would have),
        the final action of an episode keeps rew=0 with its reward riding
        the terminal marker (``flag_last_action`` semantics), terminated
        beats truncated, and a pure time-limit ending ships the pre-reset
        observation for the value bootstrap."""
        obs, act, rew = w["obs"], w["act"], w["rew"]
        term, trunc, final_obs = w["term"], w["trunc"], w["final_obs"]
        aux = w["aux"]
        aux_items = list(aux.items())
        episodes = 0
        for lane in range(self.num_envs):
            traj = self.trajectories[lane]
            for t in range(self.unroll_length):
                done = bool(term[lane, t]) or bool(trunc[lane, t])
                r = float(rew[lane, t])
                self._ep_ret[lane] += r
                record = ActionRecord(
                    obs=obs[lane, t],
                    act=np.asarray(act[lane, t]),
                    mask=None,
                    rew=0.0 if done else r,
                    reward_updated=bool(not done and r != 0.0),
                    data={k: np.asarray(v[lane, t]) for k, v in aux_items},
                    done=False,
                )
                traj.add_action(record, send_if_done=True)
                if done:
                    terminated = bool(term[lane, t])
                    time_limited = not terminated
                    marker = ActionRecord(
                        obs=(np.asarray(final_obs[lane, t], np.float32)
                             if time_limited else None),
                        rew=r, done=True, truncated=time_limited)
                    traj.add_action(marker, send_if_done=True)
                    self.episode_returns[lane].append(
                        float(self._ep_ret[lane]))
                    self._ep_ret[lane] = 0.0
                    episodes += 1
        return episodes

    # -- model hot-swap (one gate, all lanes, whole windows) --
    def maybe_swap(self, bundle: ModelBundle) -> bool:
        """Install a newer model for every lane atomically; a window in
        flight finishes on the old version, the next reads the new one
        (shared gate with PolicyActor/VectorActorHost)."""
        return apply_bundle_swap(self, bundle)

    def swap_from_bytes(self, buf: bytes) -> bool:
        return self.maybe_swap(
            ModelBundle.from_bytes(buf, params_template=ModelBundle.RAW_TREE))

    def swap_from_wire(self, version: int, blob: bytes):
        """Wire-v2-aware swap shared with the other actor hosts."""
        return apply_wire_swap(self, version, blob)


def run_anakin_loop(host, windows: int) -> list[list[float]]:
    """Drive ``windows`` fused dispatches through an
    :class:`AnakinActorHost` (or the networked anakin-mode
    ``VectorAgent`` — same ``rollout()`` surface). Returns per-lane
    completed episode returns, mirroring ``run_vector_gym_loop``."""
    for _ in range(windows):
        host.rollout()
    returns = getattr(host, "episode_returns", None)
    if returns is None:  # networked facade: reach through to the host
        returns = host.host.episode_returns
    return [list(r) for r in returns]
