"""Actor-side policy holder: inference + ActionRecord assembly + hot-swap.

This is the compute core of the reference's agent
(reference: relayrl_framework/src/network/client/agent_zmq.rs:458-571 —
``request_for_action`` runs TorchScript ``step(obs, mask)`` under no_grad,
wraps the result + ``{logp_a, v}`` into a RelayRLAction and appends it to the
trajectory; model hot-swap under a mutex at :645-679), shared by the
in-process LocalRunner and the networked Agent so both paths run identical
inference code.

The policy apply is jitted once per architecture; on actor hosts without a
TPU this compiles for CPU — the same ModelBundle serves both placements
(SURVEY.md §7.4 item 2).
"""

from __future__ import annotations

import threading
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from relayrl_tpu.models import build_policy, validate_policy
from relayrl_tpu.types.action import ActionRecord
from relayrl_tpu.types.model_bundle import (
    ModelBundle,
    arch_equal,
    exploration_kwargs,
)
from relayrl_tpu.types.trajectory import Trajectory


def resolve_actor_context(arch) -> int:
    """Serving-window length for sequence policies: the model's full
    context unless ``actor_context`` narrows it. Shared by PolicyActor
    and VectorActorHost so the positional-table guard can never drift
    between the single and batched serving paths."""
    # Same default as build_transformer_discrete (transformer.py): the
    # model's positional table is 1024 rows when the arch omits the key,
    # so the serving window must agree or context silently truncates.
    max_seq = int(arch.get("max_seq_len", 1024))
    ctx = int(arch.get("actor_context", max_seq))
    if ctx > max_seq:
        raise ValueError(
            f"actor_context {ctx} exceeds the model's max_seq_len "
            f"{max_seq} (positional table size)")
    return ctx


def push_window(window: np.ndarray, length: int, obs) -> tuple[int, bool]:
    """Advance one rolling observation-history window in place: write
    ``obs`` at ``length`` while the window is filling, else shift left by
    one and write at the end. Returns ``(new_length, rolled)``.

    This is THE window-advance rule — the single copy every tier that
    serves sequence policies goes through (PolicyActor's per-episode
    window, VectorActorHost's stacked per-lane windows, the serving
    plane's session table), and the numpy half of the parity pair with
    :func:`window_advance`, the functional JAX twin the anakin scan
    carry uses. The byte-parity contract across tiers rides on all four
    call sites advancing identically (the PR 3 window off-by-one lived
    in exactly this duplication)."""
    cap = window.shape[0]
    if length < cap:
        window[length] = obs
        return length + 1, False
    window[:-1] = window[1:]  # rolling: drop the oldest
    window[-1] = obs
    return cap, True


def window_advance(window, length, obs):
    """Functional JAX twin of :func:`push_window` for scan carries (the
    anakin tier's per-lane rolling window): fixed shapes, traced length,
    no in-place mutation. Returns ``(new_window, new_length)`` with
    exactly :func:`push_window`'s semantics — filling writes at
    ``length``, a full window shifts left and writes at ``cap - 1``,
    ``new_length`` saturates at ``cap`` (the count of real rows
    ``step_window`` expects). The numpy/JAX pair is locked row-for-row
    by tests/test_anakin.py's helper-parity golden."""
    cap = window.shape[0]
    length = jnp.asarray(length, jnp.int32)
    rolled = length >= cap
    shifted = jnp.where(rolled, jnp.roll(window, -1, axis=0), window)
    new_window = shifted.at[jnp.minimum(length, cap - 1)].set(
        jnp.asarray(obs, window.dtype))
    return new_window, jnp.minimum(length + 1, cap)


def apply_bundle_swap(actor, bundle: "ModelBundle") -> bool:
    """Shared hot-swap gate: version check, arch-ABI guard, params
    install under the actor's lock. PolicyActor and VectorActorHost
    delegate here (same attribute contract: ``version``, ``arch``,
    ``params``, ``_explore_kwargs``, ``_lock``) so the swap semantics —
    including the exploration-knob refresh that must NOT rebuild the
    policy — exist exactly once. Being the one gate also makes it the
    one swap-latency instrumentation point: the histogram measures the
    lock wait + install (what a slow batched step in flight costs every
    model delivery), and each installed version lands in the event
    journal."""
    import time

    from relayrl_tpu import telemetry

    if bundle.version <= actor.version:
        return False
    if not arch_equal(bundle.arch, actor.arch):
        raise ValueError(
            f"model arch changed {actor.arch} -> {bundle.arch}; "
            "actor refuses hot-swap (param-ABI guard)")
    from relayrl_tpu.telemetry import trace as trace_mod

    tracer = trace_mod.get_tracer()
    t0_ns = time.monotonic_ns() if tracer.enabled else 0
    t0 = time.monotonic()
    with actor._lock:
        if dict(bundle.arch) != actor.arch:
            # Exploration knobs (epsilon/act_noise) changed: they are
            # traced step arguments, so only the scalar values refresh —
            # no policy rebuild, no retrace.
            actor.arch = dict(bundle.arch)
            actor._explore_kwargs = exploration_kwargs(actor.arch)
        actor.params = bundle.params
        actor.version = bundle.version
    telemetry.get_registry().histogram(
        "relayrl_actor_swap_seconds",
        "model hot-swap: lock wait + params install").observe(
            time.monotonic() - t0)
    if tracer.enabled and tracer.sample_version(bundle.version):
        # The downstream trace's terminal hop: this actor host applied
        # the sampled version (actor field distinguishes hosts sharing
        # one process — the in-process drill's topology).
        tracer.span("model", trace_mod.model_trace_id(bundle.version),
                    "swap", t0_ns, time.monotonic_ns(),
                    version=int(bundle.version), actor=f"{id(actor):x}")
    telemetry.emit("model_swap", version=bundle.version)
    return True


def apply_wire_swap(actor, version: int, blob: bytes):
    """Shared model-delivery decode + swap for both actor hosts: sniffs
    wire-v2 frames vs legacy v1 bundles and returns the installed
    :class:`ModelBundle` (or None when nothing was installed).

    v2 path (the hot path): the frame applies into the actor's
    :class:`~relayrl_tpu.transport.modelwire.ModelWireDecoder`
    preallocated host buffers via ``np.frombuffer`` views — no flax
    ``from_bytes`` deep restore — then ONE ``jax.device_put`` of the
    assembled pytree feeds the existing :func:`apply_bundle_swap` gate.
    The device_put copies out of the buffers, so the next frame's
    in-place delta apply can never corrupt installed params (asserted by
    tests/test_model_wire.py). Installing *device* arrays also spares
    every subsequent policy dispatch the per-call host transfer.

    v1 path: legacy decode, plus a decoder reseed so a mixed-version
    fleet (v1 server, v2-capable actor) keeps the wire state coherent.

    Raises :class:`~relayrl_tpu.transport.modelwire.WireBaseMismatch`
    (once per divergence) so the transport owner can trigger a resync —
    gRPC re-polls with ``ver=-1``; broadcast planes wait out the
    keyframe interval.
    """
    from relayrl_tpu.transport import modelwire

    if not modelwire.is_wire_frame(blob):
        bundle = ModelBundle.from_bytes(blob,
                                        params_template=ModelBundle.RAW_TREE)
        bundle.version = version
        if not apply_bundle_swap(actor, bundle):
            return None
        if actor._wire_decoder is not None:
            actor._wire_decoder.seed(bundle.version, bundle.arch,
                                     bundle.params)
        return bundle
    dec = actor._wire_decoder
    if dec is None:
        dec = actor._wire_decoder = modelwire.ModelWireDecoder()
        dec.seed(actor.version, actor.arch, jax.device_get(actor.params))
    out = dec.decode(blob)
    if out is None:
        return None  # stale duplicate, or awaiting a keyframe after resync
    ver, arch, host_tree = out
    # The decoder's buffers are its LIVE delta targets — the next frame
    # mutates them in place — so the install must own its memory:
    # np.array copies first (device_put alone zero-copy aliases host
    # numpy on CPU backends; the isolation test in test_model_wire.py
    # catches exactly that), then ONE device_put of the assembled pytree
    # where a real transfer exists. On CPU actor hosts the host copies
    # install directly — same placement semantics as the v1 path, and a
    # device_put dispatch per leaf would cost more than the memcpy.
    params = jax.tree.map(np.array, host_tree)
    if jax.default_backend() != "cpu":
        params = jax.device_put(params)
    bundle = ModelBundle(version=ver, arch=arch, params=params)
    return bundle if apply_bundle_swap(actor, bundle) else None


def normalize_obs(obs) -> np.ndarray:
    """The ONE wire-dtype rule for observations entering any actor tier
    (PolicyActor, VectorActorHost, RemoteActorClient): byte frames stay
    bytes (uint8 pixel payloads are 4x smaller on the wire; the CNN
    trunk casts + scales on-device) with a defensive copy — envs
    commonly hand out views of a reused frame buffer, and a stored view
    would turn every recorded step into the episode's final frame —
    while everything else normalizes to float32. Shared so the tiers'
    byte-identical-trajectory parity can never drift on this rule."""
    obs = np.asarray(obs)
    return (obs.copy() if obs.dtype == np.uint8
            else obs.astype(np.float32, copy=False))


def make_batched_step(policy):
    """One jitted, vmapped sampling step over stacked per-lane inputs:
    ``fn(params, keys[N,2], obs[N,...], masks, explore) -> (acts, aux,
    next_keys)`` — the VectorActorHost hot path (N logical agents, one
    dispatch). Composition is exactly ``_fuse_rng(policy.step)`` per lane
    (split inside the trace, params broadcast), so a batch-of-1 call is
    bit-identical to PolicyActor's single step for the same key — the
    vector host is a batching change, not a numerics change. ``masks`` is
    ``None`` (maskless policies: no leaves, so the in_axes spec is inert)
    or a stacked ``[N, act_dim]`` array; ``explore`` is the
    :func:`exploration_kwargs` dict, broadcast as traced scalars so
    annealing a knob never retraces."""
    def _single(params, rng, obs, mask, explore):
        next_rng, sub = jax.random.split(rng)
        act, aux = policy.step(params, sub, obs, mask, **explore)
        return act, aux, next_rng

    return jax.jit(jax.vmap(_single, in_axes=(None, 0, 0, 0, None)))


def make_batched_window_step(policy):
    """Vmapped :attr:`Policy.step_window` for sequence policies:
    ``fn(params, keys[N,2], windows[N,W,obs], ts[N], masks) -> (acts, aux,
    next_keys)``. Per-lane window lengths ride as a traced vector, so
    lanes at different episode positions share one compiled signature
    (same property the single-actor padded-window path relies on)."""
    def _single(params, rng, window, t, mask):
        next_rng, sub = jax.random.split(rng)
        act, aux = policy.step_window(params, sub, window, t, mask)
        return act, aux, next_rng

    return jax.jit(jax.vmap(_single, in_axes=(None, 0, 0, 0, 0)))


def _fuse_rng(step_fn):
    """Move the per-step ``jax.random.split`` INSIDE the jitted function:
    the wrapped fn takes the carried key and returns ``(*outputs,
    next_key)``. An un-jitted split is its own XLA dispatch producing two
    device arrays — measured 162 µs/step vs 31 µs fused on a CPU actor
    host for the 2x128 MLP (81% of the reference-shaped
    ``request_for_action`` hot path, SURVEY §3.2). One dispatch per
    action, same key stream."""
    def fused(params, rng, *args, **kwargs):
        next_rng, sub = jax.random.split(rng)
        out = step_fn(params, sub, *args, **kwargs)
        return (*out, next_rng)  # every policy step returns a tuple
    return fused


class PolicyActor:
    """Local policy + current trajectory; thread-safe hot-swap."""

    def __init__(
        self,
        bundle: ModelBundle,
        max_traj_length: int = 1000,
        on_send=None,
        seed: int = 0,
        validate: bool = True,
        use_kv_cache: bool = True,
    ):
        self._lock = threading.Lock()
        self.arch = dict(bundle.arch)
        self.policy = build_policy(self.arch)
        if validate:
            validate_policy(self.policy, bundle.params)
        self.params = bundle.params
        self.version = bundle.version
        self._step_fn = jax.jit(_fuse_rng(self.policy.step))
        self._mode_fn = jax.jit(self.policy.mode)
        # Sequence policies act from a rolling obs-history window so
        # serving context matches training (ADVICE r1: context-1 serving).
        # Default window = the model's full context, so serving positions
        # match training exactly up to max_seq_len; past that the window
        # rolls (newest max_seq_len obs at positions 0..W-1), an
        # approximation since training pads/truncates from the episode
        # start — keep episodes within max_seq_len for exact parity.
        self._window_fn = None
        self._mode_window_fn = None
        self._window = None
        self._window_len = 0
        if self.policy.step_window is not None:
            ctx = resolve_actor_context(self.arch)
            self._window = np.zeros((ctx, int(self.arch["obs_dim"])),
                                    np.float32)
            self._window_fn = jax.jit(_fuse_rng(self.policy.step_window))
            if self.policy.mode_window is not None:
                self._mode_window_fn = jax.jit(self.policy.mode_window)
        # KV-cache incremental serving: O(W) per step instead of the
        # window path's O(W^2) full recompute. The window is still
        # maintained alongside — it is the replay source after a model
        # hot-swap (cache holds K/V computed by the OLD params) and the
        # fallback once an episode outgrows the context and the window
        # starts rolling (absolute positions shift, invalidating the
        # cache wholesale).
        self._cached_fn = None
        self._prefill_fn = None
        self._cache = None
        self._cache_version = -1
        if (use_kv_cache and self.policy.step_cached is not None
                and self.policy.prefill_cache is not None
                and self._window is not None):
            # prefill is required, not optional: cache rebuild (hot-swap,
            # greedy-path interleave) calls it with t > 0.
            # Donation is honored on TPU/GPU; CPU actor hosts would emit a
            # "donated buffers were not usable" warning on every step.
            donate = jax.default_backend() != "cpu"
            # _fuse_rng keeps positional order (params, rng, cache, ...),
            # so the donated cache stays argument 2.
            self._cached_fn = jax.jit(
                _fuse_rng(self.policy.step_cached),
                donate_argnums=(2,) if donate else ())
            self._prefill_fn = jax.jit(
                self.policy.prefill_cache,
                donate_argnums=(1,) if donate else ())
        self._explore_kwargs = exploration_kwargs(self.arch)
        self._rng = jax.random.PRNGKey(seed)
        # Wire-v2 decode state (preallocated per-leaf host buffers),
        # created lazily on the first v2 frame (apply_wire_swap) so
        # in-process actors that never touch the network pay nothing.
        self._wire_decoder = None
        self.trajectory = Trajectory(max_length=max_traj_length, on_send=on_send)
        from relayrl_tpu import telemetry

        self._m_steps = telemetry.get_registry().counter(
            "relayrl_actor_env_steps_total",
            "policy steps served (one per env step per lane)")

    # -- reference API (agent_zmq.rs:458-571 / o3_agent.rs:117-182) --
    def request_for_action(
        self,
        obs,
        mask=None,
        reward: float = 0.0,
    ) -> ActionRecord:
        """Run the policy, append the step to the current trajectory.

        ``reward`` is the env reward earned since the previous request —
        it is attached to the PREVIOUS record via ``update_reward`` so
        ``ActionRecord.rew`` always means "reward earned BY this action".
        The reference stores the incoming reward on the NEW record instead
        (agent_grpc.rs:434-441 builds the fresh action with it), a
        one-step credit shift its return-to-go REINFORCE tolerates but
        that inverts 1-step TD targets (DQN credited a_t with r_{t-1});
        deliberate departure, SURVEY.md §7.5 spirit. The only reward that
        can be lost is one spanning a capacity-flush chunk boundary (the
        previous record already left the process)."""
        # Byte frames stay bytes, everything else float32 — the shared
        # rule (see normalize_obs: an unconditional float32 cast here
        # silently made every "byte-sized" pixel payload 112,989 B/step
        # instead of 28,226).
        obs = normalize_obs(obs)
        mask_arr = None if mask is None else np.asarray(mask, dtype=np.float32)
        with self._lock:
            if reward and self.trajectory.get_actions():
                self.trajectory.get_actions()[-1].update_reward(float(reward))
            # The RNG split rides inside each jitted step (_fuse_rng):
            # every branch returns next_rng as its last output.
            if self._window_fn is not None:
                rolled = self._push_window(obs)
                t = self._window_len - 1
                if self._cached_fn is not None and not rolled:
                    if (self._cache is None
                            or self._cache_version != self.version):
                        self._rebuild_cache(t)
                    act, aux, self._cache, self._rng = self._cached_fn(
                        self.params, self._rng, self._cache, obs, t,
                        mask_arr)
                else:
                    self._cache = None  # rolling: positions shifted
                    act, aux, self._rng = self._window_fn(
                        self.params, self._rng, self._window,
                        self._window_len, mask_arr)
            else:
                act, aux, self._rng = self._step_fn(
                    self.params, self._rng, obs, mask_arr,
                    **self._explore_kwargs)
            record = ActionRecord(
                obs=obs,
                act=np.asarray(act),
                mask=mask_arr,
                rew=0.0,  # filled by the NEXT request / terminal marker
                data={k: np.asarray(v) for k, v in aux.items()},
                done=False,
            )
            self.trajectory.add_action(record, send_if_done=True)
        self._m_steps.inc()
        return record

    def flag_last_action(
        self,
        reward: float = 0.0,
        truncated: bool = False,
        final_obs=None,
        terminated: bool | None = None,
        final_mask=None,
    ) -> None:
        """Terminal marker: appends a done action carrying the final reward,
        which triggers the trajectory send (ref: agent_zmq.rs:605-610).

        ``truncated=True`` marks a time-limit ending (Gymnasium semantics):
        the learner then bootstraps the value target through the boundary
        instead of zeroing it. Pass the post-step observation as
        ``final_obs`` so off-policy learners have a successor state to
        bootstrap from (plus ``final_mask`` in action-masked envs, so the
        bootstrap max ranges only over actions legal in that state).
        Gymnasium can report ``terminated`` and ``truncated`` both True; a
        genuine terminal must win (no bootstrapping past a real end
        state), so callers mapping ``env.step`` output directly can pass
        ``terminated`` and let this method resolve the precedence instead
        of pre-computing it.
        """
        if terminated:
            truncated = False
        with self._lock:
            if self._window is not None:
                # Episode boundary: the next episode must not attend this
                # one's observations.
                self._window[:] = 0.0
                self._window_len = 0
                self._cache = None
            record = ActionRecord(
                obs=(None if final_obs is None
                     else np.asarray(final_obs, np.float32)),
                mask=(None if final_mask is None
                      else np.asarray(final_mask, np.float32)),
                rew=float(reward), done=True, truncated=bool(truncated))
            self.trajectory.add_action(record, send_if_done=True)

    def record_action(self, action: ActionRecord) -> None:
        """Append an externally-chosen action (the reference declares this
        but left it ``todo!()`` — agent_zmq.rs:585-596)."""
        with self._lock:
            self.trajectory.add_action(action, send_if_done=True)

    # -- model hot-swap --
    def maybe_swap(self, bundle: ModelBundle) -> bool:
        """Install a newer model; stale or arch-mismatched bundles are
        rejected (version checking the reference's proto defines but never
        implements — training_grpc.rs:722-725)."""
        return apply_bundle_swap(self, bundle)

    def swap_from_bytes(self, buf: bytes) -> bool:
        return self.maybe_swap(
            ModelBundle.from_bytes(buf, params_template=ModelBundle.RAW_TREE))

    def swap_from_wire(self, version: int, blob: bytes):
        """Wire-v2-aware swap (sniffs v1 bundles too); returns the
        installed ModelBundle or None — see :func:`apply_wire_swap`."""
        return apply_wire_swap(self, version, blob)

    def _push_window(self, obs: np.ndarray) -> bool:
        """Append one observation to the rolling history (lock held).
        Returns True once the window has started rolling."""
        self._window_len, rolled = push_window(
            self._window, self._window_len, obs)
        return rolled

    def _rebuild_cache(self, t: int) -> None:
        """Fresh cache, refilled from the stored window (lock held) —
        called lazily after a model hot-swap (old params' K/V are stale)
        or on the first cached step of an episode. One prefill dispatch
        over the full padded window (fixed shape, so one jit signature;
        padding rows write K/V that later steps overwrite in order and
        never attend before that). Masks are not replayed: they only gate
        the readout logits, never the K/V trunk."""
        self._cache = self.policy.init_cache(self._window.shape[0])
        if t > 0:
            self._cache = self._prefill_fn(self.params, self._cache,
                                           self._window)
        self._cache_version = self.version

    def reset_episode(self) -> None:
        """Reset per-episode serving state (history window + KV cache)
        WITHOUT touching the trajectory — the episode boundary for eval
        loops, where nothing must be shipped to the learner
        (flag_last_action both resets and sends)."""
        with self._lock:
            if self._window is not None:
                self._window[:] = 0.0
                self._window_len = 0
            self._cache = None

    def deterministic_action(self, obs, mask=None):
        """Greedy action. For sequence policies this ADVANCES the history
        window (greedy eval episodes need context too); call
        flag_last_action (sampling loops) or reset_episode (eval loops)
        at episode end to reset it."""
        obs_arr = np.asarray(obs, np.float32)
        mask_arr = None if mask is None else np.asarray(mask, np.float32)
        with self._lock:
            if self._mode_window_fn is not None:
                self._push_window(obs_arr)
                # The greedy path bypasses the cache but still advances the
                # window; drop the cache so the sampling path rebuilds with
                # every position present.
                self._cache = None
                act = self._mode_window_fn(self.params, self._window,
                                           self._window_len, mask_arr)
            else:
                act = self._mode_fn(self.params, obs_arr, mask_arr)
        return np.asarray(act)


def actor_aux_to_host(aux: Mapping[str, Any]) -> dict[str, np.ndarray]:
    return {k: np.asarray(v) for k, v in aux.items()}
