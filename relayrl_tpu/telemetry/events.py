"""Structured run-event journal: append-only NDJSON.

Where the metrics registry answers "how fast / how many right now", the
journal answers "what happened, in what order": model publishes and
swaps, agent register/unregister/reconnect, drops, checkpoints, drains.
One JSON object per line so the file is greppable mid-run and parseable
after a crash (the last line may be torn; every prior line is intact —
each write is flushed whole).

Every event carries the registry's ``run_id``, a wall-clock ``t_unix``
(human correlation) and a ``mono_ns`` CLOCK_MONOTONIC stamp — the same
clock the transports and the soak bench stamp receipts with, so journal
events pair against wire receipts across processes on one host (see
benches/bench_soak.py's fan-out methodology).

Event volume is run-event scale (tens per second at most: publishes,
registrations, checkpoints); the one potentially hot type — ``drop`` —
must be coalesced by the caller (the server emits one event per drop
*burst* with a count, not one per payload).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, TextIO

# The closed vocabulary instrumentation uses (free-form types are allowed
# for embedders; these are the ones docs/observability.md documents).
EVENT_TYPES = (
    "model_publish",     # server shipped a new version to the fleet
    "model_swap",        # an actor installed a new version
    "model_resync",      # a wire-v2 delta didn't fit the held base; the
                         # actor is re-pulling / awaiting a keyframe
    "agent_register",    # logical agent joined the registry
    "agent_unregister",  # logical agent left (clean exit or reaped)
    "agent_reconnect",   # agent-side transport rebuilt (restart/heal)
    "drop",              # ingest-plane loss (coalesced: carries n)
    "checkpoint",        # full-state checkpoint written
    "checkpoint_failed",  # a periodic/final save raised (carries the
                          # error + consecutive-failure count)
    "drain",             # pipeline quiesced to empty
    "heartbeat",         # liveness state transition (alive/slow/dead)
    # -- crash-recovery plane (ISSUE 6) --
    "fault_injected",    # a FaultPlan rule fired at a hook site
    "retry_exhausted",   # a RetryPolicy op spent its deadline/attempts
    "breaker_open",      # circuit breaker tripped (consecutive failures)
    "breaker_close",     # breaker closed again (successful probe/send)
    "spool_replay",      # actor re-shipped its retained trajectory window
    "duplicate_drop",    # idempotent ingest dropped replayed sequences
                         # (coalesced: carries n)
)


class EventJournal:
    """Thread-safe NDJSON appender bound to one run."""

    def __init__(self, path: str, run_id: str | None = None):
        self.path = str(path)
        self.run_id = run_id
        self._lock = threading.Lock()
        self._fh: TextIO | None = open(self.path, "a", encoding="utf-8")
        self.written = 0
        self.errors = 0

    def emit(self, event: str, **fields: Any) -> None:
        record = {"event": str(event), "run_id": self.run_id,
                  "t_unix": round(time.time(), 6),
                  "mono_ns": time.monotonic_ns()}
        for k, v in fields.items():
            record[k] = _jsonable(v)
        line = json.dumps(record, separators=(",", ":")) + "\n"
        with self._lock:
            if self._fh is None:
                return
            try:
                self._fh.write(line)
                self._fh.flush()
                self.written += 1
            except (OSError, ValueError):
                # A full disk / closed fd must never take down the plane
                # being observed.
                self.errors += 1

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


class NullJournal:
    """events_path unset: emit is a no-op attribute call."""

    path = None
    run_id = None
    written = 0

    def emit(self, event: str, **fields: Any) -> None:
        pass

    def close(self) -> None:
        pass


def _jsonable(value: Any) -> Any:
    """Journal fields must serialize without surprises: numpy scalars and
    0-d arrays become Python scalars; anything else unserializable falls
    back to ``repr`` rather than raising on the emitting thread."""
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    item = getattr(value, "item", None)
    if callable(item) and getattr(value, "ndim", 1) == 0:
        try:
            return item()
        except Exception:
            pass
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        return repr(value)


def read_events(path: str) -> list[dict]:
    """Parse a journal file, tolerating a torn final line (crash mid-
    write)."""
    out: list[dict] = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail
    return out


__all__ = ["EventJournal", "NullJournal", "read_events", "EVENT_TYPES"]
