"""Structured run-event journal: append-only NDJSON.

Where the metrics registry answers "how fast / how many right now", the
journal answers "what happened, in what order": model publishes and
swaps, agent register/unregister/reconnect, drops, checkpoints, drains.
One JSON object per line so the file is greppable mid-run and parseable
after a crash (the last line may be torn; every prior line is intact —
each write is flushed whole).

Every event carries the registry's ``run_id``, a wall-clock ``t_unix``
(human correlation) and a ``mono_ns`` CLOCK_MONOTONIC stamp — the same
clock the transports and the soak bench stamp receipts with, so journal
events pair against wire receipts across processes on one host (see
benches/bench_soak.py's fan-out methodology).

Event volume is run-event scale (tens per second at most: publishes,
registrations, checkpoints); the one potentially hot type — ``drop`` —
must be coalesced by the caller (the server emits one event per drop
*burst* with a count, not one per payload).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, TextIO

# The closed vocabulary instrumentation uses (free-form types are allowed
# for embedders; these are the ones docs/observability.md documents).
EVENT_TYPES = (
    "model_publish",     # server shipped a new version to the fleet
    "model_swap",        # an actor installed a new version
    "model_resync",      # a wire-v2 delta didn't fit the held base; the
                         # actor is re-pulling / awaiting a keyframe
    "agent_register",    # logical agent joined the registry
    "agent_unregister",  # logical agent left (clean exit or reaped)
    "agent_reconnect",   # agent-side transport rebuilt (restart/heal)
    "drop",              # ingest-plane loss (coalesced: carries n)
    "checkpoint",        # full-state checkpoint written
    "checkpoint_failed",  # a periodic/final save raised (carries the
                          # error + consecutive-failure count)
    "drain",             # pipeline quiesced to empty
    "heartbeat",         # liveness state transition (alive/slow/dead)
    # -- crash-recovery plane (ISSUE 6) --
    "fault_injected",    # a FaultPlan rule fired at a hook site
    "retry_exhausted",   # a RetryPolicy op spent its deadline/attempts
    "breaker_open",      # circuit breaker tripped (consecutive failures)
    "breaker_close",     # breaker closed again (successful probe/send)
    "spool_replay",      # actor re-shipped its retained trajectory window
    "duplicate_drop",    # idempotent ingest dropped replayed sequences
                         # (coalesced: carries n)
    # -- distributed tracing (ISSUE 14, telemetry/trace.py) --
    "trace_span",        # one sampled trace span (kind/trace/hop/proc/
                         # t0_ns/t1_ns + hop fields) — the NDJSON export
                         # of the flight recorder; volume is bounded by
                         # telemetry.trace_sample_rate + journal rotation
    # -- fleet aggregation + SLO alerts (ISSUE 15, telemetry/aggregate.py) --
    "alert_fired",       # an SLO rule's condition held through its
                         # for_s hold-down (carries rule/metric/value)
    "alert_resolved",    # the rule's condition cleared
    "fleet_evict",       # a proc went silent past telemetry.fleet_stale_s
                         # and left the fleet table
    "telemetry_exporter",  # a process started its /metrics exporter
                           # (carries url + pid — the discoverable
                           # record of per-process ephemeral ports)
    # -- guardrails plane (guardrails/) --
    "watchdog_trip",     # a watchdog predicate fired (carries rule +
                         # observed value); the halt/rollback driver
    "guardrails_halt",   # training halted by the guardrail engine
    "rollback",          # server restored a prior checkpoint/version
    "publish_blocked",   # a model publish withheld by a guardrail
    "agent_quarantined",  # agent isolated from ingest (bad traffic)
    "agent_paroled",     # quarantined agent readmitted after probation
    # -- server/relay control plane --
    "resync_keyframe_forced",  # server forced a keyframe publish because
                               # resyncs exceeded transport.resync_* caps
    "relay_up",          # relay node established its upstream session
    "relay_reconnect",   # relay upstream rebuilt after a drop
    # -- serving plane v2 (ISSUE 18, runtime/inference.py) --
    "serving_session_evicted",  # a session left the service table
                                # (carries sid + reason lru/ttl); the
                                # client answers the paired nack with a
                                # window resend, so steady-state soaks
                                # assert reason=lru count == 0
    "serving_replica_reroute",  # a mux client re-routed a session to a
                                # new replica after its home replica
                                # died (carries sid + old/new replica)
)


class EventJournal:
    """Thread-safe NDJSON appender bound to one run.

    ``max_bytes`` (``telemetry.events_max_bytes``) size-bounds the
    journal with a single-generation rotation: when an append would
    cross the bound, the current file moves to ``<path>.1`` (replacing
    any prior generation) and a fresh file opens — so a multi-hour soak
    (or the trace-span NDJSON export) holds at most ~2x ``max_bytes``
    on disk and :func:`read_events` still sees the most recent window,
    torn-tail-tolerant across the rotation boundary. 0/None disables.
    """

    def __init__(self, path: str, run_id: str | None = None,
                 max_bytes: int | None = None):
        self.path = str(path)
        self.run_id = run_id
        self.max_bytes = int(max_bytes) if max_bytes else 0
        self._lock = threading.Lock()
        self._closed = False
        self._fh: TextIO | None = open(self.path, "a", encoding="utf-8")
        try:
            self._size = self._fh.tell()
        except OSError:
            self._size = 0
        self.written = 0
        self.rotations = 0
        self.errors = 0
        self._rotate_backoff_size = 0

    def emit(self, event: str, **fields: Any) -> None:
        record = {"event": str(event), "run_id": self.run_id,
                  "t_unix": round(time.time(), 6),
                  "mono_ns": time.monotonic_ns()}
        for k, v in fields.items():
            record[k] = _jsonable(v)
        line = json.dumps(record, separators=(",", ":")) + "\n"
        with self._lock:
            if self._fh is None:
                if self._closed:
                    return
                # A failed rotation/reopen left the journal down: retry
                # the reopen per emit (counted, never silent) so a
                # transient disk condition heals instead of muting the
                # journal for the rest of the run.
                try:
                    self._fh = open(self.path, "a", encoding="utf-8")
                    self._size = self._fh.tell()
                except OSError:
                    self.errors += 1
                    return
            try:
                if (self.max_bytes and self._size
                        and self._size + len(line) > self.max_bytes
                        and self._size >= self._rotate_backoff_size):
                    try:
                        self._rotate_locked()
                    except OSError:
                        # Rotation failed (rename target unwritable,
                        # read-only dir): count it, keep APPENDING to
                        # the reopened original — the bounding mechanism
                        # must never mute the journal it bounds — and
                        # back off a full bound before retrying so a
                        # permanently-broken rename isn't re-attempted
                        # per line.
                        self.errors += 1
                        self._rotate_backoff_size = (self._size
                                                     + self.max_bytes)
                if self._fh is None:
                    raise OSError("journal file unavailable")
                self._fh.write(line)
                self._fh.flush()
                self._size += len(line)
                self.written += 1
            except (OSError, ValueError):
                # A full disk / closed fd must never take down the plane
                # being observed.
                self.errors += 1

    def _rotate_locked(self) -> None:
        """Move the full journal to ``<path>.1`` and start fresh. Lock
        held; an OSError propagates to emit's guard (one counted error),
        but the journal must come back up either way — a failed rename
        (read-only dir, ``.1`` unwritable) reopens the ORIGINAL file in
        append mode so later events still land, growing past the bound
        rather than vanishing silently (the plane being observed must
        never lose its journal to its own bounding mechanism)."""
        import os

        self._fh.close()
        self._fh = None
        try:
            os.replace(self.path, f"{self.path}.1")
        finally:
            self._fh = open(self.path, "a", encoding="utf-8")
            try:
                self._size = self._fh.tell()
            except OSError:
                self._size = 0
        self.rotations += 1
        self._rotate_backoff_size = 0

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


class NullJournal:
    """events_path unset: emit is a no-op attribute call."""

    path = None
    run_id = None
    written = 0

    def emit(self, event: str, **fields: Any) -> None:
        pass

    def close(self) -> None:
        pass


def _jsonable(value: Any) -> Any:
    """Journal fields must serialize without surprises: numpy scalars and
    0-d arrays become Python scalars; anything else unserializable falls
    back to ``repr`` rather than raising on the emitting thread."""
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    item = getattr(value, "item", None)
    if callable(item) and getattr(value, "ndim", 1) == 0:
        try:
            return item()
        except Exception:
            pass
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        return repr(value)


def read_events(path: str, include_rotated: bool = True) -> list[dict]:
    """Parse a journal file, tolerating a torn final line (crash mid-
    write). When a rotated generation (``<path>.1``) exists it is read
    FIRST so the result stays chronological across the rotation
    boundary; each file is torn-tail-tolerant independently (a crash
    can tear the live file while the rotated one is already sealed)."""
    import os

    paths = []
    if include_rotated and os.path.exists(f"{path}.1"):
        paths.append(f"{path}.1")
    paths.append(path)
    out: list[dict] = []
    for p in paths:
        try:
            fh = open(p, "r", encoding="utf-8")
        except FileNotFoundError:
            continue
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn tail
    return out


__all__ = ["EventJournal", "NullJournal", "read_events", "EVENT_TYPES"]
