"""``python -m relayrl_tpu.telemetry.top`` — one-screen live fleet summary.

Polls a telemetry exporter's ``/snapshot`` JSON endpoint and renders the
plane-by-plane view an operator wants at a glance: server ingest rates,
learner pipeline stage latencies, transport wire traffic, actor
throughput. Rates are deltas between consecutive snapshots (counters are
cumulative), so the first frame shows totals only.

Usage::

    python -m relayrl_tpu.telemetry.top [--url http://127.0.0.1:9100]
                                        [--interval 2.0] [--once]

``--once`` prints a single frame and exits (scripts, tests); the default
loops with an ANSI clear between frames until interrupted.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request

# (section title, metric-name prefix) — the render groups by prefix so a
# new instrumented subsystem shows up without touching this CLI.
_SECTIONS = (
    ("server", "relayrl_server_"),
    ("learner", "relayrl_learner_"),
    ("transport", "relayrl_transport_"),
    ("relay", "relayrl_relay_"),
    ("rlhf", "relayrl_rlhf_"),
    ("trace", "relayrl_trace_"),
    ("serving", "relayrl_serving_"),
    ("fleet", "relayrl_fleet_"),
    ("alerts", "relayrl_alert"),
    ("actor", "relayrl_actor_"),
    ("epoch", "relayrl_epoch_"),
)


def fetch_snapshot(url: str, timeout_s: float = 5.0) -> dict:
    with urllib.request.urlopen(url.rstrip("/") + "/snapshot",
                                timeout=timeout_s) as resp:
        return json.loads(resp.read().decode())


def fetch_fleet(url: str, timeout_s: float = 5.0) -> dict:
    with urllib.request.urlopen(url.rstrip("/") + "/fleet",
                                timeout=timeout_s) as resp:
        return json.loads(resp.read().decode())


def _key(entry: dict) -> str:
    labels = entry.get("labels") or {}
    if not labels:
        return entry["name"]
    body = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{entry['name']}{{{body}}}"


def histogram_quantile(entry: dict, q: float) -> float | None:
    """Linear-interpolated quantile estimate from cumulative-izable
    fixed-bucket counts (the standard Prometheus estimation)."""
    counts = entry.get("counts") or []
    total = entry.get("count") or 0
    if not total:
        return None
    bounds = list(entry["buckets"]) + [float("inf")]
    target = q * total
    cumulative = 0
    for i, (bound, n) in enumerate(zip(bounds, counts)):
        prev_cum = cumulative
        cumulative += n
        if cumulative >= target:
            if bound == float("inf"):
                return entry["buckets"][-1]  # open bucket: clamp to last bound
            lo = bounds[i - 1] if i else 0.0
            frac = (target - prev_cum) / n if n else 0.0
            return lo + (bound - lo) * frac
    return None


def _fmt_num(v: float | None) -> str:
    if v is None:  # snapshot's strict-JSON stand-in for NaN/Inf
        return "NaN"
    if abs(v) >= 1e6:
        return f"{v / 1e6:.2f}M"
    if abs(v) >= 1e3:
        return f"{v / 1e3:.1f}k"
    if isinstance(v, float) and not v.is_integer():
        return f"{v:.4g}"
    return str(int(v))


def render(snapshot: dict, prev: dict | None = None) -> str:
    """Pure snapshot(s) → screen text (tested without any HTTP)."""
    if not snapshot.get("enabled", False):
        return "telemetry disabled on the target process\n"
    lines = [
        f"relayrl telemetry · run {snapshot.get('run_id')}"
        f" · up {snapshot.get('uptime_s', 0):.0f}s"
        f" · {time.strftime('%H:%M:%S')}",
    ]
    prev_by_key = {}
    dt = None
    if prev is not None and prev.get("metrics"):
        prev_by_key = {_key(e): e for e in prev["metrics"]}
        dt = (snapshot["mono_ns"] - prev["mono_ns"]) / 1e9
        if dt <= 0:
            dt = None
    by_section: dict[str, list[str]] = {}
    for entry in snapshot.get("metrics", []):
        name = entry["name"]
        section = next((title for title, prefix in _SECTIONS
                        if name.startswith(prefix)), "other")
        short = name
        for _, prefix in _SECTIONS:
            if name.startswith(prefix):
                short = name[len(prefix):]
                break
        label_str = ""
        labels = entry.get("labels") or {}
        if labels:
            label_str = " [" + ",".join(
                f"{k}={v}" for k, v in sorted(labels.items())) + "]"
        if entry["kind"] == "histogram":
            p50 = histogram_quantile(entry, 0.5)
            p95 = histogram_quantile(entry, 0.95)
            text = (f"{short}{label_str}: n={_fmt_num(entry['count'])}"
                    + (f" p50={p50 * 1e3:.2f}ms p95={p95 * 1e3:.2f}ms"
                       if p50 is not None else ""))
        elif entry["kind"] == "counter":
            text = f"{short}{label_str}: {_fmt_num(entry['value'])}"
            old = prev_by_key.get(_key(entry))
            if (dt and old is not None and old.get("value") is not None
                    and entry.get("value") is not None):
                rate = (entry["value"] - old["value"]) / dt
                text += f" ({_fmt_num(rate)}/s)"
        else:
            text = f"{short}{label_str}: {_fmt_num(entry['value'])}"
        by_section.setdefault(section, []).append(text)
    for title, _prefix in _SECTIONS + (("other", ""),):
        rows = by_section.get(title)
        if not rows:
            continue
        lines.append(f"-- {title} " + "-" * max(1, 58 - len(title)))
        lines.extend("  " + r for r in rows)
    return "\n".join(lines) + "\n"


_TIER_ORDER = ("server", "relay", "actor", "client", "other")


def render_fleet(doc: dict, prev: dict | None = None) -> str:
    """``/fleet`` document → one merged fleet pane (ISSUE 15): an alerts
    line, per-tier proc sections, and the fleet-merged metrics grouped
    by the same plane prefixes as the single-process view. Pure
    function (tested without any HTTP)."""
    procs = doc.get("procs", [])
    tiers: dict[str, list[dict]] = {}
    for p in procs:
        tiers.setdefault(p.get("tier", "other"), []).append(p)
    tier_counts = " ".join(f"{t}={len(tiers[t])}" for t in _TIER_ORDER
                           if t in tiers)
    lines = [f"relayrl fleet · {len(procs)} proc(s) · {tier_counts}"
             f" · stale_s {doc.get('stale_s')}"
             f" · {time.strftime('%H:%M:%S')}"]
    alerts = doc.get("alerts") or []
    active = [a for a in alerts if a.get("active")]
    if active:
        parts = ", ".join(
            f"{a['name']}({_fmt_num(a.get('value'))} {a.get('op')} "
            f"{_fmt_num(a.get('threshold'))})" for a in active)
        lines.append(f"ALERTS: {len(active)} active — {parts}")
    else:
        lines.append(f"alerts: none active ({len(alerts)} rule(s) armed)")
    for tier in _TIER_ORDER:
        rows = tiers.get(tier)
        if not rows:
            continue
        lines.append(f"-- {tier} " + "-" * max(1, 58 - len(tier)))
        for p in sorted(rows, key=lambda r: r.get("proc", "")):
            extra = (f" · restarts {p['restarts']}"
                     if p.get("restarts") else "")
            up = p.get("uptime_s")
            lines.append(
                f"  {p.get('proc')} · age {p.get('age_s', '?')}s"
                + (f" · up {up:.0f}s" if isinstance(up, (int, float))
                   else "") + extra)
    merged = doc.get("merged") or {}
    if merged.get("metrics"):
        # No rate column: merged docs carry no shared monotonic clock.
        lines.append("== fleet merged " + "=" * 47)
        lines.append(render(dict(merged, enabled=True, run_id="fleet",
                                 uptime_s=0.0)).split("\n", 1)[1])
    return "\n".join(lines).rstrip("\n") + "\n"


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m relayrl_tpu.telemetry.top",
        description="one-screen live summary of a relayrl telemetry "
                    "endpoint")
    parser.add_argument("--url", default="http://127.0.0.1:9100",
                        help="exporter base URL (default %(default)s)")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh seconds (default %(default)s)")
    parser.add_argument("--once", action="store_true",
                        help="print one frame and exit")
    parser.add_argument("--fleet", action="store_true",
                        help="render the ROOT server's merged fleet pane "
                             "(/fleet: per-tier proc sections, alerts "
                             "line, fleet-merged metrics) instead of the "
                             "single-process /snapshot view")
    args = parser.parse_args(argv)
    endpoint = "fleet" if args.fleet else "snapshot"
    prev = None
    try:
        while True:
            try:
                snapshot = (fetch_fleet(args.url) if args.fleet
                            else fetch_snapshot(args.url))
            except (urllib.error.URLError, OSError, ValueError) as e:
                print(f"cannot reach {args.url}/{endpoint}: {e}",
                      file=sys.stderr)
                return 1
            frame = (render_fleet(snapshot, prev) if args.fleet
                     else render(snapshot, prev))
            if args.once:
                sys.stdout.write(frame)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + frame)
            sys.stdout.flush()
            prev = snapshot
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
