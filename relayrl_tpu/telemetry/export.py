"""Telemetry exporter: Prometheus text exposition + JSON snapshot over a
stdlib ``http.server`` thread.

Endpoints (GET):

* ``/metrics``  — Prometheus text exposition format 0.0.4 (the scrape
  surface; conformance locked by tests/test_telemetry.py).
* ``/snapshot`` — the registry's structured JSON snapshot verbatim (the
  schema ``telemetry.top`` and the soak-bench rows consume — one schema
  for live scrapes and committed artifacts).
* ``/healthz``  — liveness stub for probes.

The server is a daemon ``ThreadingHTTPServer`` so a slow scraper never
blocks a second one, and every handler only *reads* a snapshot — the
registry's hot paths (per-thread shard ``+=``) proceed untouched while
an export renders. Device-valued gauges resolve inside the handler
thread (the snapshot contract), so a scrape can fence device work but
the learner/actor threads never do.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_CONTENT_TYPE_PROM = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label_value(value: str) -> str:
    return (value.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _render_labels(labels: dict, extra: list[tuple[str, str]] = ()) -> str:
    items = [(k, str(v)) for k, v in sorted(labels.items())] + list(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in items)
    return "{" + body + "}"


def _fmt(value: float | None) -> str:
    if value is None:
        # Snapshot's strict-JSON stand-in for a non-finite value; the
        # text format does allow a NaN literal.
        return "NaN"
    if value == float("inf"):
        return "+Inf"
    if isinstance(value, float) and value.is_integer():
        return str(int(value))
    return repr(float(value))


def render_prometheus(snapshot: dict) -> str:
    """Registry snapshot → Prometheus text exposition.

    Conformance points the tests pin: one ``# HELP``/``# TYPE`` pair per
    metric family (not per labeled child), histogram children named
    ``<name>_bucket`` with CUMULATIVE ``le`` counts ending at ``+Inf``,
    plus ``<name>_sum``/``<name>_count``, and a trailing newline."""
    families: dict[str, list[dict]] = {}
    order: list[str] = []
    for entry in snapshot.get("metrics", []):
        name = entry["name"]
        if name not in families:
            families[name] = []
            order.append(name)
        families[name].append(entry)
    lines: list[str] = []
    for name in order:
        children = families[name]
        help_text = next((c["help"] for c in children if c.get("help")), "")
        kind = children[0]["kind"]
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        for child in children:
            labels = child.get("labels", {})
            if child["kind"] == "histogram":
                cumulative = 0
                bounds = list(child["buckets"]) + [float("inf")]
                for bound, count in zip(bounds, child["counts"]):
                    cumulative += count
                    lines.append(
                        f"{name}_bucket"
                        f"{_render_labels(labels, [('le', _fmt(bound))])}"
                        f" {cumulative}")
                lines.append(
                    f"{name}_sum{_render_labels(labels)} "
                    f"{_fmt(child['sum'])}")
                lines.append(
                    f"{name}_count{_render_labels(labels)} {child['count']}")
            else:
                lines.append(
                    f"{name}{_render_labels(labels)} {_fmt(child['value'])}")
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    # set per-server via the factory in TelemetryExporter
    registry = None
    # Fleet aggregation (telemetry/aggregate.py): the root training
    # server installs its FleetTable (+ AlertEngine) via
    # TelemetryExporter.set_fleet, enabling /fleet and /fleet/metrics.
    fleet = None
    alerts = None

    def do_GET(self):  # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0]
        if path in ("/metrics", "/"):
            body = render_prometheus(self.registry.snapshot()).encode()
            self._reply(200, _CONTENT_TYPE_PROM, body)
        elif path == "/fleet":
            fleet = type(self).fleet
            if fleet is None:
                self._reply(404, "application/json",
                            b'{"error": "no fleet table on this process '
                            b'(telemetry.fleet_interval_s off, or not the '
                            b'root server)"}\n')
                return
            body = json.dumps(fleet.document(alerts=type(self).alerts),
                              allow_nan=False).encode()
            self._reply(200, "application/json", body)
        elif path == "/fleet/metrics":
            fleet = type(self).fleet
            if fleet is None:
                self._reply(404, "text/plain", b"no fleet table\n")
                return
            self._reply(200, _CONTENT_TYPE_PROM,
                        fleet.prometheus_text().encode())
        elif path == "/snapshot":
            # allow_nan=False is a tripwire, not a formatter: the
            # snapshot contract already nulls non-finite values.
            body = json.dumps(self.registry.snapshot(),
                              allow_nan=False).encode()
            self._reply(200, "application/json", body)
        elif path == "/traces":
            # The distributed-tracing flight recorder (telemetry/
            # trace.py): the process tracer's live span ring. Served
            # even when tracing is disabled (an empty, enabled=false
            # document) so fleet pollers need no probe-then-fetch dance.
            from relayrl_tpu.telemetry import trace as _trace

            body = json.dumps(_trace.traces_document(),
                              allow_nan=False).encode()
            self._reply(200, "application/json", body)
        elif path == "/healthz":
            self._reply(200, "text/plain", b"ok\n")
        else:
            self._reply(404, "text/plain", b"not found\n")

    def _reply(self, code: int, ctype: str, body: bytes) -> None:
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper hung up mid-reply; nothing to clean up

    def log_message(self, fmt, *args):
        pass  # scrape chatter must not pollute training logs


class TelemetryExporter:
    """HTTP exporter bound to one registry. ``port=0`` binds an ephemeral
    port (tests, multi-process fleets on one host); read the resolved
    one from :attr:`port`."""

    def __init__(self, registry, port: int = 0, host: str = "127.0.0.1"):
        self.registry = registry
        handler = type("_BoundHandler", (_Handler,), {"registry": registry})
        self._httpd = ThreadingHTTPServer((host, int(port)), handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="telemetry-exporter",
            daemon=True)
        self._thread.start()
        # The journal is the discoverable record of ephemeral ports: a
        # fleet operator greps `telemetry_exporter` events instead of
        # scraping stdout for per-process bind lines.
        from relayrl_tpu import telemetry

        telemetry.emit("telemetry_exporter", url=self.url,
                       pid=os.getpid())

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def set_fleet(self, fleet, alerts=None) -> None:
        """Install the fleet table (+ alert engine) behind ``/fleet`` and
        ``/fleet/metrics``. Called by the root training server AFTER the
        exporter is up (construction order: telemetry serves first, the
        fleet plane builds later)."""
        handler = self._httpd.RequestHandlerClass
        handler.fleet = fleet
        handler.alerts = alerts

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


__all__ = ["TelemetryExporter", "render_prometheus"]
