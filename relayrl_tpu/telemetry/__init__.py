"""Telemetry: the framework's first-class observability subsystem.

Three pieces (ISSUE 4):

* :mod:`relayrl_tpu.telemetry.core`   — metrics registry (counters,
  gauges, fixed-bucket histograms) with per-thread shards and a null
  registry for disabled mode;
* :mod:`relayrl_tpu.telemetry.export` — Prometheus text + JSON snapshot
  endpoints on a stdlib http.server thread;
* :mod:`relayrl_tpu.telemetry.events` — append-only NDJSON run-event
  journal (publish/swap/register/drop/checkpoint/drain).

Process model: ONE registry + ONE journal per process, owned by this
module. Instrumentation sites (server, pipeline, transports, actors,
epoch logger) call :func:`get_registry` / :func:`emit` at construction
time and hold direct metric references — when telemetry is disabled
those are null objects and the hot-path cost is a single attribute call
(benches/bench_telemetry.py commits the numbers).

Enablement: the first :class:`~relayrl_tpu.config.ConfigLoader`-bearing
component in a process (TrainingServer, Agent, VectorAgent) calls
:func:`configure_from_config`, which reads the ``telemetry.*`` section
(docs/observability.md has the knob table) and installs a real
:class:`~relayrl_tpu.telemetry.core.Registry` + journal once; later
calls are no-ops so a server and an in-process agent can't fight over
it. Embedders and benches can instead install a registry directly with
:func:`set_registry` and serve it with :func:`serve`.

Consume with Prometheus against ``/metrics``, any JSON poller against
``/snapshot``, or the bundled one-screen CLI::

    python -m relayrl_tpu.telemetry.top --url http://127.0.0.1:9100
"""

from __future__ import annotations

import threading

from relayrl_tpu.telemetry.core import (  # noqa: F401
    DEFAULT_TIME_BUCKETS,
    NULL_METRIC,
    Counter,
    Gauge,
    GaugeFn,
    Histogram,
    NullRegistry,
    Registry,
)
from relayrl_tpu.telemetry.events import (  # noqa: F401
    EVENT_TYPES,
    EventJournal,
    NullJournal,
    read_events,
)
from relayrl_tpu.telemetry.export import (  # noqa: F401
    TelemetryExporter,
    render_prometheus,
)

_state_lock = threading.Lock()
_registry = NullRegistry()
_journal = NullJournal()
_exporter: TelemetryExporter | None = None
_configured = False
_serve_port: int | None = None
_serve_host = "127.0.0.1"


def get_registry():
    """The process-wide registry (a :class:`NullRegistry` until telemetry
    is enabled). Instrumentation sites call this once at construction
    and keep the metric objects it hands out."""
    return _registry


def set_registry(registry) -> None:
    """Install a registry explicitly (benches, tests, embedders). Marks
    the process configured so a later config-driven component doesn't
    overwrite it."""
    global _registry, _configured
    with _state_lock:
        _registry = registry
        _configured = True


def get_journal():
    return _journal


def set_journal(journal) -> None:
    global _journal
    with _state_lock:
        _journal = journal


def emit(event: str, **fields) -> None:
    """Append one run event to the process journal (no-op when no
    journal is configured). See events.EVENT_TYPES for the vocabulary."""
    _journal.emit(event, **fields)


def configure_from_config(config) -> object:
    """Idempotently configure this process's telemetry from a
    :class:`~relayrl_tpu.config.ConfigLoader` (the ``telemetry.*``
    section). First caller wins; every caller gets the live registry
    back. Does NOT start the HTTP exporter — the component that owns the
    port (the training server) calls :func:`maybe_serve` after this."""
    global _registry, _journal, _configured, _serve_port, _serve_host
    with _state_lock:
        if _configured:
            return _registry
        params = config.get_telemetry_params()
        _configured = True
        if not params.get("enabled"):
            return _registry
        _registry = Registry(run_id=params.get("run_id") or None)
        _serve_port = params.get("port")
        _serve_host = params.get("host", "127.0.0.1")
        events_path = params.get("events_path")
        if events_path:
            try:
                _journal = EventJournal(
                    str(events_path), run_id=_registry.run_id,
                    max_bytes=params.get("events_max_bytes") or 0)
            except OSError as e:
                print(f"[telemetry] event journal unavailable "
                      f"({events_path}): {e!r}", flush=True)
        # Distributed tracing (telemetry/trace.py): sample_rate 0 (the
        # default) leaves the shared null tracer installed — every span
        # site then costs one attribute check.
        rate = params.get("trace_sample_rate") or 0.0
        if rate > 0:
            from relayrl_tpu.telemetry import trace as _trace

            _trace.configure(rate, ring=params.get("trace_ring", 4096))
        return _registry


def serve(port: int = 0, host: str = "127.0.0.1") -> TelemetryExporter:
    """Start (or return) the process exporter for the live registry."""
    global _exporter
    with _state_lock:
        if _exporter is None:
            _exporter = TelemetryExporter(_registry, port=port, host=host)
        return _exporter


def maybe_serve() -> TelemetryExporter | None:
    """Start the exporter iff telemetry was config-enabled with a port.
    Called by the training server (the one component per host expected
    to own ``telemetry.port``); returns None when disabled. A bind
    failure (port already held — two servers on one host, a stale
    process) degrades to metrics-without-exporter with a loud note: the
    observability plane must never take down the process it observes."""
    if not _registry.enabled or _serve_port is None:
        return None
    try:
        exporter = serve(port=int(_serve_port), host=_serve_host)
    except OSError as e:
        print(f"[telemetry] exporter bind failed on "
              f"{_serve_host}:{_serve_port} ({e!r}) — metrics stay "
              f"in-process only (set telemetry.port to a free port, or 0 "
              f"for ephemeral)", flush=True)
        return None
    print(f"[telemetry] serving /metrics and /snapshot at {exporter.url}",
          flush=True)
    return exporter


def shutdown() -> None:
    """Stop the exporter and close the journal (tests / clean exits).
    The registry stays — counters are cumulative for the process life."""
    global _exporter
    with _state_lock:
        if _exporter is not None:
            _exporter.close()
            _exporter = None
        _journal.close()


def reset_for_tests() -> None:
    """Restore pristine disabled state (test isolation only)."""
    global _registry, _journal, _exporter, _configured, _serve_port
    with _state_lock:
        if _exporter is not None:
            _exporter.close()
            _exporter = None
        _journal.close()
        _registry = NullRegistry()
        _journal = NullJournal()
        _configured = False
        _serve_port = None
    from relayrl_tpu.telemetry import trace as _trace

    _trace.reset_for_tests()


__all__ = [
    "Registry", "NullRegistry", "Counter", "Gauge", "GaugeFn", "Histogram",
    "EventJournal", "NullJournal", "TelemetryExporter", "render_prometheus",
    "read_events", "EVENT_TYPES", "DEFAULT_TIME_BUCKETS", "NULL_METRIC",
    "get_registry", "set_registry", "get_journal", "set_journal", "emit",
    "configure_from_config", "serve", "maybe_serve", "shutdown",
    "reset_for_tests",
]
