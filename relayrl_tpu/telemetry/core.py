"""Metrics core: counters, gauges, fixed-bucket histograms behind
per-thread shards.

Design constraints (ISSUE 4 tentpole, part 1):

* **Hot-path cost is a plain ``+=``.** Every counter/histogram hands each
  thread its own shard cell (created once per thread, cached on a
  ``threading.local``), so the increment path takes no lock and touches
  no shared cache line; aggregation across cells is deferred to
  :meth:`Registry.snapshot`. Cells of exited threads are kept — counters
  are cumulative, exactly the Prometheus semantic.
* **Disabled mode is a null object.** When ``telemetry.enabled`` is
  false the process-global registry is a :class:`NullRegistry` whose
  metrics are one shared do-nothing object — instrumentation sites hold
  a direct metric reference, so the disabled cost is a single attribute
  call (``self._m_steps.inc()``), measured by
  ``benches/bench_telemetry.py``.
* **JAX-aware: never fence a dispatch.** :meth:`Gauge.set` stores
  whatever it is given — a host float or an in-flight device scalar —
  and resolves to a host float only inside :meth:`Registry.snapshot`
  (the same deferral as ``runtime/pipeline.LazyMetrics``: the fence
  happens where the value is *read*, at export time, never on the
  thread that dispatched it). Histograms take host floats only (their
  bucketing is a comparison, which on a device value would be a sync);
  time them with :meth:`Histogram.time` around host-side work.
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from typing import Any, Callable, Iterable, Mapping

# Shared latency bucket ladder (seconds): sub-millisecond policy steps up
# through multi-second publish/checkpoint stalls.
DEFAULT_TIME_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def log_buckets(lo: float, hi: float, per_decade: int = 3) -> tuple[float, ...]:
    """Log-spaced histogram bounds from ``lo`` to at least ``hi``
    (seconds), ``per_decade`` buckets per power of ten, rounded to two
    significant digits so the grid is stable across platforms. The
    preset builder for sites whose dynamic range outgrows the fixed
    default grid at relay/pod scale (ISSUE 14 bucket audit)."""
    if lo <= 0 or hi <= lo:
        raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
    ratio = 10.0 ** (1.0 / max(1, int(per_decade)))
    out: list[float] = []
    v = float(lo)
    while True:
        r = float(f"{v:.2g}")
        if not out or r > out[-1]:
            out.append(r)
        if r >= hi:
            break
        v *= ratio
    return tuple(out)


# Wide per-op latency grid: 100 µs .. 60 s. The audit preset for sites
# that saturate the default grid under fleet fan-out — model delivery on
# a backed-up SUB thread, sends through an open-breaker stall, serving
# requests queued behind an overload — where the old 10 s top bucket
# pinned every tail sample in +Inf.
LATENCY_BUCKETS_WIDE = log_buckets(1e-4, 60.0, per_decade=3)

# End-to-end age grid (distributed tracing): 1 ms .. 600 s. Data age
# (env-step → consumed-by-update) and model age (publish → applied)
# legitimately reach minutes under pacing/backpressure; the top finite
# bucket matches the cross-host skew guard's 300 s bound with headroom.
AGE_BUCKETS = log_buckets(1e-3, 600.0, per_decade=3)


def _canon_labels(labels: Mapping[str, str] | None) -> tuple[tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _resolve_scalar(value: Any) -> float | None:
    """Host-float view of a recorded value. Device arrays fence HERE (the
    snapshot/export thread), never where they were recorded. None means
    "no value" (dead/failed source) and omits the sample."""
    if value is None:
        return None
    try:
        return float(value)
    except Exception:
        return None


def _json_safe(value: float) -> float | None:
    """Strict-JSON value: NaN/Inf → None (a diverged stat still shows up,
    as null, without poisoning the whole document)."""
    return value if math.isfinite(value) else None


class _Cell:
    """One thread's private accumulator (counter: ``value``; histogram:
    ``counts``/``sum``/``count``)."""

    __slots__ = ("value", "counts", "sum", "count")

    def __init__(self, n_buckets: int = 0):
        self.value = 0.0
        if n_buckets:
            self.counts = [0] * n_buckets
            self.sum = 0.0
            self.count = 0


class _ShardedMetric:
    """Base for metrics whose hot path writes a per-thread cell."""

    def __init__(self, name: str, help_text: str,
                 labels: tuple[tuple[str, str], ...], n_buckets: int = 0):
        self.name = name
        self.help = help_text
        self.labels = labels
        self._n_buckets = n_buckets
        self._local = threading.local()
        self._cells: list[_Cell] = []
        self._cells_lock = threading.Lock()

    def _cell(self) -> _Cell:
        try:
            return self._local.cell
        except AttributeError:
            cell = _Cell(self._n_buckets)
            with self._cells_lock:
                self._cells.append(cell)
            self._local.cell = cell
            return cell

    def _all_cells(self) -> list[_Cell]:
        with self._cells_lock:
            return list(self._cells)


class Counter(_ShardedMetric):
    """Monotonic accumulator. ``inc`` is the hot path: one
    threading.local read + one ``+=`` on a private cell."""

    kind = "counter"

    def inc(self, n: float = 1.0) -> None:
        self._cell().value += n

    def total(self) -> float:
        return sum(c.value for c in self._all_cells())


class Gauge:
    """Last-write-wins scalar. ``set`` is a plain attribute assignment
    (atomic under the GIL, no lock); the stored value may be an
    unresolved device scalar — :meth:`read` fences it at snapshot time
    only (the LazyMetrics deferral)."""

    kind = "gauge"

    def __init__(self, name: str, help_text: str,
                 labels: tuple[tuple[str, str], ...]):
        self.name = name
        self.help = help_text
        self.labels = labels
        self._value: Any = 0.0

    def set(self, value: Any) -> None:
        self._value = value

    def inc(self, n: float = 1.0) -> None:
        # Convenience for host-float gauges only (occupancy counts); a
        # read-modify-write on a device handle would resolve it, so make
        # the read explicit and cheap.
        v = self._value
        self._value = (v if isinstance(v, (int, float)) else 0.0) + n

    def read(self) -> float | None:
        return _resolve_scalar(self._value)


class GaugeFn:
    """Gauge whose value is pulled from a callable at snapshot time —
    zero hot-path cost (queue depths, registry sizes, window occupancy
    read straight from the live object when someone actually looks)."""

    kind = "gauge"

    def __init__(self, name: str, help_text: str,
                 labels: tuple[tuple[str, str], ...], fn: Callable[[], Any]):
        self.name = name
        self.help = help_text
        self.labels = labels
        self._fn = fn

    def read(self) -> float | None:
        try:
            return _resolve_scalar(self._fn())
        except Exception:
            return None  # a dead source must not break the whole export


class _NullTimer:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class _Timer:
    __slots__ = ("_hist", "_t0")

    def __init__(self, hist: "Histogram"):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.monotonic() - self._t0)
        return False


class Histogram(_ShardedMetric):
    """Fixed-bucket histogram. ``observe`` is the hot path: a bisect into
    a small tuple + three ``+=`` on the thread's private cell. Bucket
    bounds are upper bounds; an implicit +Inf bucket catches the rest."""

    kind = "histogram"

    def __init__(self, name: str, help_text: str,
                 labels: tuple[tuple[str, str], ...],
                 buckets: Iterable[float] = DEFAULT_TIME_BUCKETS):
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError(f"histogram {name!r} needs at least one bucket")
        super().__init__(name, help_text, labels, n_buckets=len(bounds) + 1)
        self.buckets = bounds

    def observe(self, value: float) -> None:
        cell = self._cell()
        cell.counts[bisect.bisect_left(self.buckets, value)] += 1
        cell.sum += value
        cell.count += 1

    def time(self) -> _Timer:
        return _Timer(self)

    def totals(self) -> tuple[list[int], float, int]:
        counts = [0] * (len(self.buckets) + 1)
        total, n = 0.0, 0
        for cell in self._all_cells():
            for i, c in enumerate(cell.counts):
                counts[i] += c
            total += cell.sum
            n += cell.count
        return counts, total, n


class Registry:
    """Process metrics registry: get-or-create by (name, labels), one
    structured :meth:`snapshot` consumed by the Prometheus exporter, the
    JSON endpoint, ``telemetry.top`` and the soak bench rows (one
    schema everywhere — the acceptance bar)."""

    enabled = True

    def __init__(self, run_id: str | None = None):
        import os

        self.run_id = run_id or f"run-{os.getpid()}-{int(time.time())}"
        self.created_unix = time.time()
        self._lock = threading.Lock()
        self._metrics: dict[tuple[str, tuple], Any] = {}

    def _get_or_create(self, name: str, labels, factory, kind: str):
        key = (name, _canon_labels(labels))
        with self._lock:
            metric = self._metrics.get(key)
            if metric is None:
                metric = factory(key[1])
                self._metrics[key] = metric
            elif metric.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {metric.kind}, "
                    f"requested {kind}")
            return metric

    def counter(self, name: str, help_text: str = "",
                labels: Mapping[str, str] | None = None) -> Counter:
        return self._get_or_create(
            name, labels, lambda lb: Counter(name, help_text, lb), "counter")

    def gauge(self, name: str, help_text: str = "",
              labels: Mapping[str, str] | None = None) -> Gauge:
        return self._get_or_create(
            name, labels, lambda lb: Gauge(name, help_text, lb), "gauge")

    def gauge_fn(self, name: str, fn: Callable[[], Any],
                 help_text: str = "",
                 labels: Mapping[str, str] | None = None) -> GaugeFn:
        """Pull-gauge: re-registering the same name rebinds the source
        (a restarted server's fresh queue replaces the dead one's) —
        but only gauge-over-gauge; clobbering a counter/histogram and
        its accumulated shards stays an error like everywhere else."""
        key = (name, _canon_labels(labels))
        metric = GaugeFn(name, help_text, key[1], fn)
        with self._lock:
            existing = self._metrics.get(key)
            if existing is not None and existing.kind != "gauge":
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{existing.kind}, requested gauge")
            self._metrics[key] = metric
        return metric

    def histogram(self, name: str, help_text: str = "",
                  labels: Mapping[str, str] | None = None,
                  buckets: Iterable[float] = DEFAULT_TIME_BUCKETS) -> Histogram:
        return self._get_or_create(
            name, labels,
            lambda lb: Histogram(name, help_text, lb, buckets), "histogram")

    def snapshot(self) -> dict:
        """Structured point-in-time view. Device-valued gauges resolve
        HERE (the exporter/snapshot thread pays the fence, never the
        recording thread); the metric list is copied out of the lock
        first so a slow resolution cannot stall concurrent hot-path
        shard creation."""
        with self._lock:
            metrics = list(self._metrics.values())
        out = []
        for m in metrics:
            entry = {"name": m.name, "kind": m.kind,
                     "labels": dict(m.labels)}
            if m.help:
                entry["help"] = m.help
            # Non-finite values become JSON null, never bare NaN/Inf: the
            # snapshot is served as strict JSON (/snapshot, bench rows)
            # and a diverging run's NaN loss must not make the whole
            # document unparseable at exactly the moment an operator
            # needs it. The Prometheus renderer maps null back to NaN
            # (legal in the text format).
            if m.kind == "counter":
                entry["value"] = _json_safe(m.total())
            elif m.kind == "gauge":
                value = m.read()
                if value is None:
                    continue  # unresolvable source: omit, don't break export
                entry["value"] = _json_safe(value)
            else:
                counts, total, n = m.totals()
                entry.update(buckets=list(m.buckets), counts=counts,
                             sum=_json_safe(total), count=n)
            out.append(entry)
        out.sort(key=lambda e: (e["name"], sorted(e["labels"].items())))
        return {
            "schema": "relayrl-telemetry-v1",
            "run_id": self.run_id,
            "enabled": True,
            "time_unix": time.time(),
            "mono_ns": time.monotonic_ns(),
            "uptime_s": round(time.time() - self.created_unix, 3),
            "metrics": out,
        }


class _NullMetric:
    """One shared do-nothing metric: the disabled hot path is a single
    attribute call on this object."""

    __slots__ = ()
    kind = "null"

    def inc(self, n: float = 1.0) -> None:
        pass

    def set(self, value: Any) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    def time(self) -> _NullTimer:
        return _NULL_TIMER

    def read(self):
        return None

    def total(self) -> float:
        return 0.0


_NULL_TIMER = _NullTimer()
NULL_METRIC = _NullMetric()


class NullRegistry:
    """telemetry.enabled=false: every factory returns the shared null
    metric, snapshot is a stub — no shards, no exporter, no cost."""

    enabled = False
    run_id = None

    def counter(self, name: str, help_text: str = "", labels=None):
        return NULL_METRIC

    def gauge(self, name: str, help_text: str = "", labels=None):
        return NULL_METRIC

    def gauge_fn(self, name: str, fn, help_text: str = "", labels=None):
        return NULL_METRIC

    def histogram(self, name: str, help_text: str = "", labels=None,
                  buckets=DEFAULT_TIME_BUCKETS):
        return NULL_METRIC

    def snapshot(self) -> dict:
        return {"schema": "relayrl-telemetry-v1", "enabled": False,
                "run_id": None, "metrics": []}


__all__ = [
    "Counter", "Gauge", "GaugeFn", "Histogram", "Registry", "NullRegistry",
    "NULL_METRIC", "DEFAULT_TIME_BUCKETS", "LATENCY_BUCKETS_WIDE",
    "AGE_BUCKETS", "log_buckets",
]
