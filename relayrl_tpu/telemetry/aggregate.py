"""Fleet telemetry aggregation over the relay tree (ISSUE 15).

The metrics plane was O(processes): every actor/relay exports
``/metrics`` on an ephemeral port that only ever appears in stdout, so a
1k-actor soak had no single pane of glass. This module makes fleet
rollup a first-class plane of the disaggregated dataflow (RLAX
arXiv:2512.06392, MindSpeed RL arXiv:2507.19017), riding the planes the
tree already has:

* **Snapshot frames** — a versioned compact wire frame (``RLS1`` magic +
  msgpack) carrying one or more per-process *sections*: proc identity,
  tier, process epoch, frame seq, and the registry's ``/snapshot``
  document verbatim. Frames ship through the ordinary trajectory
  transport beside trajectories (no new socket): the envelope id is the
  untagged ``@fleet/<proc>`` marker, the payload is sniffed by magic at
  every ingest funnel exactly like columnar ``RLD1`` frames.
* **Merge semantics** — :func:`merge_snapshots` is THE one merge
  implementation (benches pool soak-row snapshots through it too):
  counters sum, gauges keep min/max/sum/count across procs (the
  per-proc latest lives in the fleet table), histograms sum bucket-wise
  (the shared bucket presets make grids compatible; mismatches are
  counted, never mixed). Merging is commutative and associative by
  construction — a merged document can be merged again.
* **Fleet table** — the root's per-proc store. Counter merging is
  EPOCH-AWARE: when a process restarts (its registry's ``created_unix``
  epoch bumps) the old epoch's counter values fold into a per-proc
  baseline, so a restarted process never makes a fleet counter go
  backwards. Procs that stop reporting evict after
  ``telemetry.fleet_stale_s``.
* **Relay fan-in** — a relay buffers its subtree's frames
  (:class:`FleetRelayBuffer`, latest-per-proc, epoch/seq ordered) and
  forwards ONE multi-proc frame per interval with every section
  verbatim, so root ingest cost is O(relays) exactly like the model
  plane. Sections are never re-stamped: the root's epoch logic needs
  the leaf's own epoch/seq.
* **SLO alerts** — declarative ``telemetry.alerts`` rules (metric
  selector, aggregation, threshold, ``for_s`` hold-down) evaluated over
  the merged snapshot each interval at the root, emitting
  ``alert_fired``/``alert_resolved`` journal events and
  ``relayrl_alert_active{rule}`` gauges. :func:`default_alert_rules`
  ships the stock pack (drops, open breakers, guardrail halt,
  non-finite publish blocked, ingest queue depth, trace data-age p95).

Consume at the root: ``GET /fleet`` (JSON), ``GET /fleet/metrics``
(Prometheus text with ``proc``/``tier`` labels), or
``python -m relayrl_tpu.telemetry.top --fleet``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Iterable, Mapping

import msgpack

# -- snapshot frames ---------------------------------------------------------

SNAP_MAGIC = b"RLS1"
FRAME_VERSION = 1

#: Envelope-id prefix for fleet snapshot frames. Untagged on purpose: no
#: ``#s`` seq (telemetry is latest-wins — a replayed stale snapshot is
#: worse than a dropped one, so frames never enter a spool) and no
#: ``#t`` trace context.
FLEET_WIRE_PREFIX = "@fleet/"

_TIERS = ("server", "relay", "actor", "client", "other")


def fleet_wire_id(proc: str) -> str:
    return f"{FLEET_WIRE_PREFIX}{proc}"


def is_snapshot_frame(payload) -> bool:
    """Cheap magic sniff — the ingest funnels call this on EVERY payload
    (like the columnar ``RLD1`` sniff), so it must be a slice compare."""
    return bytes(payload[:4]) == SNAP_MAGIC


def snapshot_section(snapshot: Mapping, proc: str, tier: str,
                     epoch: float, seq: int) -> dict:
    """One per-process section of a snapshot frame. ``snapshot`` is the
    registry's ``/snapshot`` document verbatim (the one schema
    everywhere); ``epoch`` identifies the process LIFE (the registry's
    ``created_unix`` — a restart mints a new one), ``seq`` orders frames
    within an epoch."""
    return {
        "proc": str(proc),
        "tier": str(tier) if tier in _TIERS else "other",
        "epoch": float(epoch),
        "seq": int(seq),
        "t_unix": time.time(),
        "snapshot": dict(snapshot),
    }


def encode_snapshot_frame(sections: Iterable[Mapping]) -> bytes:
    return SNAP_MAGIC + msgpack.packb(
        {"v": FRAME_VERSION, "procs": list(sections)}, use_bin_type=True)


def parse_snapshot_frame(payload) -> list[dict]:
    """Frame → sections. Raises ``ValueError`` on anything malformed (the
    transport swallow-classifier's droppable class), including a section
    missing its identity fields — a frame that cannot be attributed to a
    proc cannot be merged."""
    if not is_snapshot_frame(payload):
        raise ValueError("not a snapshot frame (RLS1 magic missing)")
    try:
        doc = msgpack.unpackb(bytes(payload[4:]), raw=False)
    except Exception as e:  # msgpack raises its own hierarchy
        raise ValueError(f"snapshot frame undecodable: {e!r}") from e
    if not isinstance(doc, dict) or int(doc.get("v", -1)) != FRAME_VERSION:
        raise ValueError("snapshot frame version/shape mismatch")
    sections = doc.get("procs")
    if not isinstance(sections, list):
        raise ValueError("snapshot frame carries no sections")
    out = []
    for s in sections:
        if not isinstance(s, dict) or not s.get("proc") \
                or not isinstance(s.get("snapshot"), dict):
            raise ValueError("snapshot section missing proc/snapshot")
        try:
            s["epoch"] = float(s.get("epoch", 0.0))
            s["seq"] = int(s.get("seq", 0))
        except (TypeError, ValueError) as e:
            raise ValueError(f"snapshot section bad epoch/seq: {e!r}") from e
        out.append(s)
    return out


# -- merge semantics ---------------------------------------------------------

def _canon_key(entry: Mapping) -> tuple:
    labels = entry.get("labels") or {}
    return (entry.get("name"),
            tuple(sorted((str(k), str(v)) for k, v in labels.items())))


def merge_snapshots(snapshots: Iterable[Mapping]) -> dict:
    """Deterministically merge registry ``/snapshot`` documents into one.

    Per (name, labels) family child:

    * **counters** sum (``None`` — the strict-JSON stand-in for a
      non-finite value — contributes nothing);
    * **histograms** sum bucket-wise when the grids match; a grid
      mismatch keeps the first grid and counts the skipped child in
      ``grid_mismatches`` (never mixes incompatible buckets);
    * **gauges** aggregate to ``{value: sum, min, max, sum, count}`` —
      the fleet total plus the spread. Already-merged gauge entries
      (carrying ``count``) fold by their components, which is what makes
      the merge associative: ``merge([merge([a, b]), c]) ==
      merge([a, b, c])``.

    The output is itself snapshot-schema (``metrics`` sorted like
    ``Registry.snapshot``), so every existing consumer — the Prometheus
    renderer, ``histogram_quantile``, the bench pooling — reads it
    unchanged.
    """
    merged: dict[tuple, dict] = {}
    order: list[tuple] = []
    n_snaps = 0
    mismatches = 0
    for snap in snapshots:
        n_snaps += 1
        for m in (snap or {}).get("metrics", []):
            kind = m.get("kind")
            key = _canon_key(m)
            cur = merged.get(key)
            if kind == "counter":
                v = m.get("value")
                if cur is None:
                    cur = {"name": m["name"], "kind": "counter",
                           "labels": dict(m.get("labels") or {}),
                           "value": 0.0}
                    if m.get("help"):
                        cur["help"] = m["help"]
                    merged[key] = cur
                    order.append(key)
                if v is not None:
                    cur["value"] += v
            elif kind == "histogram":
                if cur is None:
                    cur = {"name": m["name"], "kind": "histogram",
                           "labels": dict(m.get("labels") or {}),
                           "buckets": list(m["buckets"]),
                           "counts": list(m["counts"]),
                           "sum": m.get("sum") or 0.0,
                           "count": int(m.get("count") or 0)}
                    if m.get("help"):
                        cur["help"] = m["help"]
                    merged[key] = cur
                    order.append(key)
                elif cur.get("buckets") != list(m["buckets"]):
                    mismatches += 1
                else:
                    for i, c in enumerate(m["counts"]):
                        cur["counts"][i] += c
                    cur["sum"] += m.get("sum") or 0.0
                    cur["count"] += int(m.get("count") or 0)
            elif kind == "gauge":
                # Raw gauge: {value}; merged gauge: {value(sum), min,
                # max, sum, count}. Fold either shape.
                if m.get("count") is not None and "min" in m:
                    g_sum, g_min = m.get("sum"), m.get("min")
                    g_max, g_n = m.get("max"), int(m["count"])
                else:
                    v = m.get("value")
                    if v is None:
                        g_n = 0
                        g_sum = g_min = g_max = None
                    else:
                        g_sum = g_min = g_max = v
                        g_n = 1
                if cur is None:
                    cur = {"name": m["name"], "kind": "gauge",
                           "labels": dict(m.get("labels") or {}),
                           "value": 0.0, "min": None, "max": None,
                           "sum": 0.0, "count": 0}
                    if m.get("help"):
                        cur["help"] = m["help"]
                    merged[key] = cur
                    order.append(key)
                if g_n:
                    cur["sum"] += g_sum
                    cur["count"] += g_n
                    cur["min"] = (g_min if cur["min"] is None
                                  else min(cur["min"], g_min))
                    cur["max"] = (g_max if cur["max"] is None
                                  else max(cur["max"], g_max))
                    cur["value"] = cur["sum"]
    out = [merged[k] for k in order]
    out.sort(key=lambda e: (e["name"], sorted(e["labels"].items())))
    return {
        "schema": "relayrl-telemetry-v1",
        "enabled": True,
        "merged": True,
        "merged_from": n_snaps,
        "grid_mismatches": mismatches,
        "time_unix": time.time(),
        "metrics": out,
    }


def snapshot_metric(snap: Mapping, name: str,
                    labels: Mapping | None = None) -> float | None:
    """One scalar out of a snapshot document, labels matched as a SUBSET
    (instance-distinguishing labels the caller doesn't care about must
    not break the lookup). The shared helper the benches used to
    re-implement privately."""
    want = {str(k): str(v) for k, v in (labels or {}).items()}
    for m in snap.get("metrics", []):
        if m.get("name") != name:
            continue
        have = m.get("labels") or {}
        if all(have.get(k) == v for k, v in want.items()):
            return m.get("value")
    return None


# -- fleet table (root-side per-proc store) ----------------------------------

class _ProcEntry:
    __slots__ = ("proc", "tier", "epoch", "seq", "t_unix", "snapshot",
                 "last_seen", "base", "restarts")

    def __init__(self, section: Mapping, now: float):
        self.proc = section["proc"]
        self.tier = section.get("tier", "other")
        self.epoch = section["epoch"]
        self.seq = section["seq"]
        self.t_unix = section.get("t_unix")
        self.snapshot = section["snapshot"]
        self.last_seen = now
        # Prior-epoch accumulation: key -> ("counter", value) |
        # ("histogram", counts, sum, count). The fleet-counter
        # monotonicity contract across process restarts.
        self.base: dict[tuple, tuple] = {}
        self.restarts = 0


def _fold_base(base: dict, snapshot: Mapping) -> None:
    """Accumulate a finished epoch's cumulative families into ``base``
    (counters AND histograms — both are cumulative and both would
    regress fleet-wide when a restarted process reports from zero)."""
    for m in snapshot.get("metrics", []):
        key = _canon_key(m)
        kind = m.get("kind")
        if kind == "counter":
            v = m.get("value")
            if v is None:
                continue
            old = base.get(key)
            base[key] = ("counter", (old[1] if old else 0.0) + v)
        elif kind == "histogram":
            old = base.get(key)
            counts = list(m["counts"])
            h_sum = m.get("sum") or 0.0
            h_n = int(m.get("count") or 0)
            if old and old[0] == "histogram" and len(old[1]) == len(counts):
                counts = [a + b for a, b in zip(old[1], counts)]
                h_sum += old[2]
                h_n += old[3]
            base[key] = ("histogram", counts, h_sum, h_n,
                         list(m.get("buckets") or ()))


def _effective_snapshot(entry: _ProcEntry) -> dict:
    """The proc's snapshot with prior-epoch baselines added back in.
    Verbatim (no copy, bit-exact) when the proc never restarted — the
    common case, and the acceptance drill's exactness bar."""
    if not entry.base:
        return entry.snapshot
    metrics = []
    seen: set[tuple] = set()
    for m in entry.snapshot.get("metrics", []):
        key = _canon_key(m)
        seen.add(key)
        old = entry.base.get(key)
        if old is None:
            metrics.append(m)
        elif old[0] == "counter" and m.get("kind") == "counter":
            adj = dict(m)
            adj["value"] = (adj.get("value") or 0.0) + old[1]
            metrics.append(adj)
        elif (old[0] == "histogram" and m.get("kind") == "histogram"
                and len(old[1]) == len(m.get("counts") or ())):
            adj = dict(m)
            adj["counts"] = [a + b for a, b in zip(old[1], m["counts"])]
            adj["sum"] = (adj.get("sum") or 0.0) + old[2]
            adj["count"] = int(adj.get("count") or 0) + old[3]
            metrics.append(adj)
        else:
            metrics.append(m)
    # Families the new life never registered (yet) still carry their
    # prior-epoch totals — dropping them would regress the fleet sum.
    for key, old in entry.base.items():
        if key in seen:
            continue
        name, labels = key
        if old[0] == "counter":
            metrics.append({"name": name, "kind": "counter",
                            "labels": dict(labels), "value": old[1]})
        else:
            metrics.append({"name": name, "kind": "histogram",
                            "labels": dict(labels),
                            "buckets": list(old[4]),
                            "counts": list(old[1]), "sum": old[2],
                            "count": old[3]})
    snap = dict(entry.snapshot)
    snap["metrics"] = metrics
    return snap


class FleetTable:
    """The root's fleet store: latest snapshot per proc with epoch-aware
    counter baselines and staleness eviction. Thread-safe — transport
    threads ingest while the fleet tick and exporter handlers read."""

    #: Bounded proc store (the relay subtree-registry precedent): a
    #: forged-frame flood must not grow the table without limit.
    MAX_PROCS = 65536

    def __init__(self, stale_s: float = 15.0, registry=None):
        from relayrl_tpu import telemetry

        reg = registry if registry is not None else telemetry.get_registry()
        self.stale_s = float(stale_s)
        self._lock = threading.Lock()
        self._entries: dict[str, _ProcEntry] = {}
        self._local_seq = 0
        self._m_frames = reg.counter(
            "relayrl_fleet_frames_total",
            "snapshot frames ingested at this table (O(relays) at the "
            "root of a relay tree)")
        self._m_sections = reg.counter(
            "relayrl_fleet_sections_total",
            "per-proc sections ingested (O(procs))")
        self._m_stale_sections = reg.counter(
            "relayrl_fleet_stale_sections_total",
            "sections dropped: out of order (older epoch/seq than the "
            "held one) or past the bounded proc-store cap")
        self._m_evicted = reg.counter(
            "relayrl_fleet_evicted_total",
            "procs evicted after telemetry.fleet_stale_s of silence")
        self._m_restarts = reg.counter(
            "relayrl_fleet_restarts_total",
            "epoch bumps observed (a proc restarted; its prior-epoch "
            "counters folded into the monotonic baseline)")
        # Weak source (the server pull-gauge precedent): the registry is
        # process-global and must not pin a replaced table's proc store.
        import weakref

        wref = weakref.ref(self)
        reg.gauge_fn(
            "relayrl_fleet_procs",
            lambda: (lambda t: None if t is None else t.proc_count())(
                wref()),
            "processes currently reporting in the fleet table")

    def proc_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def ingest_frame(self, payload) -> int:
        """One wire frame (possibly multi-proc, from a relay). Raises
        ``ValueError`` on malformed frames — callers sit behind the
        standard decode-error narrowing."""
        sections = parse_snapshot_frame(payload)
        self._m_frames.inc()
        return self.ingest_sections(sections)

    def ingest_sections(self, sections: Iterable[Mapping],
                        now: float | None = None) -> int:
        now = time.monotonic() if now is None else now
        accepted = 0
        with self._lock:
            for s in sections:
                self._m_sections.inc()
                e = self._entries.get(s["proc"])
                if e is None:
                    if len(self._entries) >= self.MAX_PROCS:
                        self._m_stale_sections.inc()
                        continue
                    self._entries[s["proc"]] = _ProcEntry(s, now)
                    accepted += 1
                    continue
                if s["epoch"] > e.epoch:
                    # Restart: fold the finished life's cumulative
                    # families into the baseline FIRST (the base dict
                    # already carries any earlier epochs), so the fleet
                    # totals never go backwards.
                    _fold_base(e.base, e.snapshot)
                    e.epoch = s["epoch"]
                    e.seq = s["seq"]
                    e.restarts += 1
                    self._m_restarts.inc()
                elif s["epoch"] < e.epoch or s["seq"] < e.seq:
                    self._m_stale_sections.inc()
                    continue
                else:
                    e.seq = s["seq"]
                e.tier = s.get("tier", e.tier)
                e.t_unix = s.get("t_unix", e.t_unix)
                e.snapshot = s["snapshot"]
                e.last_seen = now
                accepted += 1
        return accepted

    def ingest_registry(self, registry, proc: str, tier: str) -> None:
        """Join a LOCAL registry (the root server's own) without a wire
        hop; epoch is the registry's ``created_unix`` like every remote
        section."""
        self._local_seq += 1
        self.ingest_sections([snapshot_section(
            registry.snapshot(), proc, tier,
            getattr(registry, "created_unix", 0.0), self._local_seq)])

    def sweep(self, now: float | None = None) -> list[str]:
        """Evict procs silent past ``stale_s``; returns the evicted proc
        ids (the caller journals them — this module never imports the
        journal so benches can use the table standalone)."""
        now = time.monotonic() if now is None else now
        evicted = []
        with self._lock:
            for proc, e in list(self._entries.items()):
                if now - e.last_seen > self.stale_s:
                    del self._entries[proc]
                    evicted.append(proc)
        if evicted:
            self._m_evicted.inc(len(evicted))
        return evicted

    def procs(self, now: float | None = None) -> list[dict]:
        now = time.monotonic() if now is None else now
        with self._lock:
            entries = sorted(self._entries.values(), key=lambda e: e.proc)
            return [{
                "proc": e.proc,
                "tier": e.tier,
                "epoch": e.epoch,
                "seq": e.seq,
                "restarts": e.restarts,
                "age_s": round(max(0.0, now - e.last_seen), 3),
                "run_id": e.snapshot.get("run_id"),
                "uptime_s": e.snapshot.get("uptime_s"),
            } for e in entries]

    def proc_snapshot(self, proc: str) -> dict | None:
        """One proc's effective (baseline-adjusted) snapshot."""
        with self._lock:
            e = self._entries.get(proc)
            return None if e is None else _effective_snapshot(e)

    def merged(self) -> dict:
        """The fleet-merged snapshot: every proc's effective snapshot in
        sorted-proc order through :func:`merge_snapshots` — one
        deterministic float-addition order, the drill's bit-exactness
        contract."""
        with self._lock:
            snaps = [_effective_snapshot(e) for e in sorted(
                self._entries.values(), key=lambda e: e.proc)]
        return merge_snapshots(snaps)

    def document(self, alerts: "AlertEngine | None" = None) -> dict:
        """The ``/fleet`` JSON document."""
        doc = {
            "schema": "relayrl-fleet-v1",
            "time_unix": time.time(),
            "stale_s": self.stale_s,
            "procs": self.procs(),
            "merged": self.merged(),
        }
        doc["alerts"] = alerts.describe() if alerts is not None else []
        return doc

    def prometheus_text(self) -> str:
        """Per-proc series with ``proc``/``tier`` labels — the merged
        Prometheus scrape surface (``/fleet/metrics``): the grid a
        Prometheus server would itself aggregate across."""
        from relayrl_tpu.telemetry.export import render_prometheus

        with self._lock:
            entries = sorted(self._entries.values(), key=lambda e: e.proc)
            rows = []
            for e in entries:
                for m in _effective_snapshot(e).get("metrics", []):
                    child = dict(m)
                    labels = dict(m.get("labels") or {})
                    labels["proc"] = e.proc
                    labels["tier"] = e.tier
                    child["labels"] = labels
                    rows.append(child)
        return render_prometheus({"metrics": rows})


# -- SLO alert engine --------------------------------------------------------

_ALERT_AGGS = ("sum", "max", "min", "avg", "increase",
               "p50", "p95", "p99", "count")
_ALERT_OPS: dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}


class AlertRule:
    """One declarative SLO rule over the merged fleet snapshot.

    ``agg`` picks the reduction over matching children: ``sum``/``max``/
    ``min``/``avg`` for scalars, ``p50``/``p95``/``p99``/``count`` for
    histograms, ``increase`` for counters (delta between consecutive
    evaluations, clamped at 0 — the "is it STILL happening" form that a
    cumulative counter can't express). ``for_s`` is the hold-down: the
    condition must hold continuously that long before the alert fires
    (0 = fire on first observation); resolution is immediate."""

    def __init__(self, name: str, metric: str, agg: str = "sum",
                 op: str = ">", threshold: float = 0.0,
                 for_s: float = 0.0, labels: Mapping | None = None):
        if not name or not metric:
            raise ValueError("alert rule needs name and metric")
        if agg not in _ALERT_AGGS:
            raise ValueError(f"alert {name!r}: agg {agg!r} not in "
                             f"{_ALERT_AGGS}")
        if op not in _ALERT_OPS:
            raise ValueError(f"alert {name!r}: op {op!r} not in "
                             f"{tuple(_ALERT_OPS)}")
        self.name = str(name)
        self.metric = str(metric)
        self.agg = agg
        self.op = op
        self.threshold = float(threshold)
        self.for_s = max(0.0, float(for_s))
        self.labels = {str(k): str(v) for k, v in (labels or {}).items()}

    @classmethod
    def from_dict(cls, d: Mapping) -> "AlertRule":
        allowed = {"name", "metric", "agg", "op", "threshold", "for_s",
                   "labels"}
        unknown = set(d) - allowed
        if unknown:
            raise ValueError(f"alert rule has unknown keys {sorted(unknown)}")
        if "name" not in d or "metric" not in d:
            raise ValueError(f"alert rule needs name and metric, got {d!r}")
        return cls(**{k: d[k] for k in allowed if k in d})

    def describe(self) -> dict:
        return {"name": self.name, "metric": self.metric, "agg": self.agg,
                "op": self.op, "threshold": self.threshold,
                "for_s": self.for_s, "labels": self.labels}


def default_alert_rules() -> list[AlertRule]:
    """The stock rule pack — every signature already has a runbook row
    (docs/operations.md): data loss, a stuck transport, a halted
    learner, blocked non-finite publishes, ingest backlog, stale data
    reaching updates."""
    return [
        AlertRule("ingest_drops", "relayrl_server_dropped_total",
                  agg="increase", op=">", threshold=0.0),
        AlertRule("breaker_open", "relayrl_breaker_state",
                  agg="max", op=">=", threshold=2.0),
        AlertRule("guardrail_halt", "relayrl_guard_halted",
                  agg="max", op=">", threshold=0.0),
        AlertRule("nonfinite_publish_blocked",
                  "relayrl_guard_publish_blocked_total",
                  agg="increase", op=">", threshold=0.0),
        AlertRule("ingest_queue_depth", "relayrl_server_ingest_queue_depth",
                  agg="max", op=">", threshold=50_000.0, for_s=5.0),
        AlertRule("trace_data_age_p95", "relayrl_trace_data_age_seconds",
                  agg="p95", op=">", threshold=60.0, for_s=10.0),
    ]


def rules_from_config(params: Mapping) -> list[AlertRule]:
    """``telemetry.alerts`` + the default pack (unless
    ``telemetry.alerts_default_pack`` is false). A malformed user rule
    warns and is skipped — the alert plane must never take down the
    process it watches. User rules override same-named defaults."""
    import warnings

    rules: dict[str, AlertRule] = {}
    if params.get("alerts_default_pack", True):
        for r in default_alert_rules():
            rules[r.name] = r
    user = params.get("alerts")
    if isinstance(user, (list, tuple)):
        for d in user:
            try:
                r = AlertRule.from_dict(d)
            except (ValueError, TypeError) as e:
                warnings.warn(f"ignoring invalid telemetry.alerts rule "
                              f"{d!r}: {e}")
                continue
            rules[r.name] = r
    return [rules[k] for k in sorted(rules)]


class _RuleState:
    __slots__ = ("active", "pending_since", "last_raw", "last_value")

    def __init__(self):
        self.active = False
        self.pending_since: float | None = None
        self.last_raw: float | None = None
        self.last_value: float | None = None


class AlertEngine:
    """Evaluates rules over consecutive merged snapshots, with journal
    events + per-rule active gauges as the outputs. Single-threaded by
    contract (the root's fleet tick drives it)."""

    def __init__(self, rules: Iterable[AlertRule], registry=None,
                 emit=None):
        from relayrl_tpu import telemetry

        reg = registry if registry is not None else telemetry.get_registry()
        self._emit = emit if emit is not None else telemetry.emit
        self.rules = list(rules)
        self._state = {r.name: _RuleState() for r in self.rules}
        self._gauges = {
            r.name: reg.gauge("relayrl_alert_active",
                              "1 while this SLO alert rule is firing",
                              {"rule": r.name})
            for r in self.rules}
        self._m_fired = reg.counter(
            "relayrl_alerts_fired_total", "alert rule activations")
        self._last_membership: frozenset | None = None
        for g in self._gauges.values():
            g.set(0)

    def _value(self, merged: Mapping, rule: AlertRule) -> float | None:
        matching = [m for m in merged.get("metrics", [])
                    if m.get("name") == rule.metric
                    and all((m.get("labels") or {}).get(k) == v
                            for k, v in rule.labels.items())]
        if not matching:
            return None
        if rule.agg in ("p50", "p95", "p99", "count"):
            hists = [m for m in matching if m.get("kind") == "histogram"]
            if not hists:
                return None
            # Strip labels so children with distinct label sets (e.g.
            # backend=zmq/grpc) pool into ONE distribution for the rule.
            pooled = merge_snapshots(
                [{"metrics": [{**m, "labels": {}} for m in hists]}]
            )["metrics"]
            agg = pooled[0] if pooled else None
            if agg is None or not agg.get("count"):
                return None
            if rule.agg == "count":
                return float(agg["count"])
            from relayrl_tpu.telemetry.top import histogram_quantile

            return histogram_quantile(agg, float(rule.agg[1:]) / 100.0)
        scalars = [m for m in matching
                   if m.get("kind") in ("counter", "gauge")]
        if not scalars:
            return None
        if rule.agg in ("sum", "increase"):
            values = [m.get("value") for m in scalars
                      if m.get("value") is not None]
            return float(sum(values)) if values else None

        # max/min/avg must range over PER-PROC values, and a merged
        # gauge child collapses those into value=sum — but it carries
        # the spread (min/max/sum/count) for exactly this read. A rule
        # like spool_depth max > N must fire on the worst PROCESS, not
        # on the fleet-wide sum of healthy depths.
        def spread(m, field):
            if m.get("kind") == "gauge" and m.get("count") is not None \
                    and field in m:
                return m.get(field)
            return m.get("value")

        if rule.agg == "max":
            values = [spread(m, "max") for m in scalars]
            values = [v for v in values if v is not None]
            return float(max(values)) if values else None
        if rule.agg == "min":
            values = [spread(m, "min") for m in scalars]
            values = [v for v in values if v is not None]
            return float(min(values)) if values else None
        # avg: pooled mean across procs/children where the merged entry
        # knows its sample count; raw entries count 1.
        total = n = 0.0
        for m in scalars:
            if m.get("kind") == "gauge" and m.get("count") is not None:
                if m["count"]:
                    total += m.get("sum") or 0.0
                    n += m["count"]
            elif m.get("value") is not None:
                total += m["value"]
                n += 1
        return float(total / n) if n else None

    def evaluate(self, merged: Mapping, now: float | None = None,
                 membership: Iterable[str] | None = None) -> list[dict]:
        """One evaluation pass; returns the transitions (fired/resolved)
        it made, already journaled and reflected in the gauges.

        ``membership`` (the proc-id set behind ``merged``, passed by the
        fleet tick) guards the ``increase`` rules against table churn: a
        proc evicting drops its whole cumulative counter out of the
        merged sum, and its REJOIN re-adds the lifetime total in one
        step — a delta that would read as an enormous spurious increase.
        On any membership change, increase rules rebaseline (one skipped
        observation) instead of firing on the step."""
        now = time.monotonic() if now is None else now
        rebaseline = False
        if membership is not None:
            current = frozenset(membership)
            rebaseline = (self._last_membership is not None
                          and current != self._last_membership)
            self._last_membership = current
        transitions = []
        for rule in self.rules:
            state = self._state[rule.name]
            value = self._value(merged, rule)
            if rule.agg == "increase":
                raw = value
                if value is None or state.last_raw is None or rebaseline:
                    value = None
                else:
                    value = max(0.0, value - state.last_raw)
                state.last_raw = raw
            state.last_value = value
            firing = (value is not None
                      and _ALERT_OPS[rule.op](value, rule.threshold))
            if firing:
                if state.active:
                    continue
                if state.pending_since is None:
                    state.pending_since = now
                if now - state.pending_since >= rule.for_s:
                    state.active = True
                    state.pending_since = None
                    self._gauges[rule.name].set(1)
                    self._m_fired.inc()
                    self._emit("alert_fired", rule=rule.name,
                               metric=rule.metric, value=value,
                               threshold=rule.threshold)
                    transitions.append({"rule": rule.name,
                                        "event": "alert_fired",
                                        "value": value})
            else:
                state.pending_since = None
                if state.active:
                    state.active = False
                    self._gauges[rule.name].set(0)
                    self._emit("alert_resolved", rule=rule.name,
                               metric=rule.metric)
                    transitions.append({"rule": rule.name,
                                        "event": "alert_resolved"})
        return transitions

    def active(self) -> list[str]:
        return [r.name for r in self.rules if self._state[r.name].active]

    def describe(self) -> list[dict]:
        out = []
        for rule in self.rules:
            state = self._state[rule.name]
            d = rule.describe()
            d["active"] = state.active
            d["value"] = state.last_value
            out.append(d)
        return out


# -- push path: per-process emitter + relay fan-in ---------------------------

class FleetEmitter:
    """Periodic snapshot-frame emitter for one process: every
    ``interval_s`` the registry's snapshot ships as a single-section
    frame through ``send_fn(frame_bytes, wire_id)`` — the caller binds
    its agent transport's ``send_trajectory`` so the frame rides beside
    trajectories on the existing connection. Send failures count and
    never escape (telemetry must not crash the loop it observes)."""

    def __init__(self, send_fn: Callable[[bytes, str], Any], proc: str,
                 tier: str, interval_s: float, registry=None,
                 start: bool = True):
        from relayrl_tpu import telemetry

        self._registry = (registry if registry is not None
                          else telemetry.get_registry())
        self._send_fn = send_fn
        self.proc = str(proc)
        self.tier = str(tier)
        self.interval_s = max(0.05, float(interval_s))
        self.epoch = float(getattr(self._registry, "created_unix", 0.0))
        self._seq = 0
        self._seq_lock = threading.Lock()
        reg = self._registry
        self._m_emitted = reg.counter(
            "relayrl_fleet_frames_emitted_total",
            "snapshot frames this process shipped upstream")
        self._m_errors = reg.counter(
            "relayrl_fleet_emit_errors_total",
            "snapshot-frame sends that failed (dropped; next interval "
            "carries fresher data anyway)")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if start:
            self._thread = threading.Thread(
                target=self._loop, name=f"fleet-emit-{self.proc}",
                daemon=True)
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.emit_now()

    def emit_now(self) -> bool:
        try:
            with self._seq_lock:
                self._seq += 1
                seq = self._seq
            frame = encode_snapshot_frame([snapshot_section(
                self._registry.snapshot(), self.proc, self.tier,
                self.epoch, seq)])
            self._send_fn(frame, fleet_wire_id(self.proc))
        except Exception:
            self._m_errors.inc()
            return False
        self._m_emitted.inc()
        return True

    def close(self, final: bool = True) -> None:
        """Stop the thread; ``final`` ships one last frame so the root's
        table holds this life's closing totals (the drill's exactness
        fence)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if final:
            self.emit_now()


class FleetRelayBuffer:
    """A relay's subtree fan-in: latest section per proc (epoch, then
    seq ordered — a restarted leaf's fresh epoch replaces the old one),
    drained once per interval into ONE multi-proc frame upstream.
    Sections forward VERBATIM: the root's epoch-aware baselines need
    the leaf's own stamps, so a relay never re-stamps or merges values
    — it compresses FRAME COUNT (O(relays) at the root), not content."""

    MAX_PROCS = 65536  # the FleetTable bound, one hop down

    def __init__(self):
        self._lock = threading.Lock()
        self._latest: dict[str, dict] = {}
        self._dirty: set[str] = set()

    def ingest_frame(self, payload) -> int:
        return self.ingest_sections(parse_snapshot_frame(payload))

    def ingest_sections(self, sections: Iterable[Mapping]) -> int:
        n = 0
        with self._lock:
            for s in sections:
                held = self._latest.get(s["proc"])
                if held is None and len(self._latest) >= self.MAX_PROCS:
                    continue
                if held is not None and (
                        s["epoch"] < held["epoch"]
                        or (s["epoch"] == held["epoch"]
                            and s["seq"] < held["seq"])):
                    continue
                self._latest[s["proc"]] = dict(s)
                self._dirty.add(s["proc"])
                n += 1
        return n

    def drain(self) -> list[dict]:
        """Sections updated since the last drain, sorted by proc. A leaf
        that went quiet is not re-forwarded — root staleness owns
        eviction, and re-sending frozen counters would mask it."""
        with self._lock:
            out = [self._latest[p] for p in sorted(self._dirty)
                   if p in self._latest]
            self._dirty.clear()
        return out

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._latest)


__all__ = [
    "SNAP_MAGIC", "FLEET_WIRE_PREFIX", "fleet_wire_id",
    "is_snapshot_frame", "snapshot_section", "encode_snapshot_frame",
    "parse_snapshot_frame", "merge_snapshots", "snapshot_metric",
    "FleetTable", "AlertRule", "AlertEngine", "default_alert_rules",
    "rules_from_config", "FleetEmitter", "FleetRelayBuffer",
]
