"""End-to-end distributed tracing: per-trajectory and per-model-version
span propagation with critical-path attribution (ISSUE 14).

The metrics plane (``telemetry/core.py``) answers "how fast is each
stage"; this module answers "where did THIS trajectory's 40 ms go" and
"why did this actor swap version N late" — the cross-process causal view
Podracer-style disaggregated designs (arXiv:2104.06272) and dataflow RL
systems (MindSpeed RL, arXiv:2507.19017) treat as a first-class
debugging surface.

Two trace kinds, both sampled at ``telemetry.trace_sample_rate``:

* ``traj`` — one sampled trajectory, traced **upstream** from env-step /
  window production through columnar encode, spool/send, (relay
  batch-forward,) server ingest, dedup, staging decode, and the update
  dispatch that consumed it. The trace context rides the wire as a
  suffix on the envelope agent id — ``<agent>#t<ctx>#s<seq>`` — beside
  the spool's ``#s`` seq tag, so zmq/grpc/native and relay hops all
  carry it without a new wire version (the native C++ core carries
  envelope ids verbatim; RLD1 frames and RLB1 containers are untouched).
* ``model`` — one sampled model version, traced **downstream** from
  learner dispatch through fence, wire-v2 encode, publish, (relay
  re-broadcast,) actor receipt, and swap. No wire context is needed:
  every process samples versions with the same deterministic hash
  (:meth:`Tracer.sample_version`), so all hops of a sampled version
  record spans independently and the analyzer joins them by version.

Spans land in a bounded in-memory flight recorder (``telemetry.
trace_ring`` entries, oldest evicted) and are exported three ways:

* NDJSON — every span also lands in the events journal as a
  ``trace_span`` event (rotation-bounded, ``telemetry.events_max_bytes``);
* ``/traces`` on the telemetry exporter — the live ring as JSON;
* Chrome-trace JSON (:func:`to_chrome_trace`) loadable in
  ``chrome://tracing`` / Perfetto.

On top sits the critical-path analyzer::

    python -m relayrl_tpu.telemetry.trace events.ndjson [--url http://...]
        [--json] [--chrome out.json]

which reduces sampled traces to per-hop latency attribution plus the two
numbers the metrics plane cannot produce: end-to-end **data age**
(env-step → consumed-by-update) and **model age** (dispatch →
applied-at-actor) distributions. The same ages are observed live into
``relayrl_trace_data_age_seconds`` / ``relayrl_trace_model_age_seconds``
(surfaced by ``telemetry.top`` and embedded in bench_soak rows).

Clock discipline: every stamp is CLOCK_MONOTONIC ``monotonic_ns()`` —
comparable across processes on ONE host (the soak-bench fan-out
methodology). Cross-host pairs inherit the PR 4 skew guard: an age
outside ``[0, 300 s)`` is dropped as skew, never observed, and the
analyzer applies the same bound when joining spans from different
journals. Disabled mode is a shared :data:`NULL_TRACER` whose every
surface is a no-op attribute call — the instrumented sites cost one
``.enabled`` check (ceilings committed by ``benches/bench_telemetry.py``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from relayrl_tpu.transport.base import (  # noqa: F401 (re-exported)
    split_agent_trace,
    tag_agent_trace,
)

# Upstream (trajectory) hops in causal order; the analyzer sorts by this.
TRAJ_HOPS = ("env", "encode", "send", "relay", "ingest", "dedup",
             "staging", "update")
# Downstream (model-version) hops in causal order.
MODEL_HOPS = ("dispatch", "fence", "encode", "publish", "relay",
              "receipt", "swap")
# Serving / RLHF stage hops (self-contained per-plane attribution).
SERVE_HOPS = ("queue", "dispatch")
RLHF_HOPS = ("generate", "score", "emit")

_HOP_ORDER = {h: i for i, h in enumerate(TRAJ_HOPS)}
_MODEL_ORDER = {h: i for i, h in enumerate(MODEL_HOPS)}

# The PR 4 cross-host monotonic skew guard, in ns: CLOCK_MONOTONIC is
# per-boot, so cross-host pairs are off by the uptime delta in EITHER
# direction; nothing on these planes legitimately takes 300 s.
SKEW_GUARD_NS = int(300e9)


class TrajCtx:
    """The trajectory trace context that rides the wire: a trace id plus
    the origin stamps the server needs to compute data age (born_ns,
    CLOCK_MONOTONIC at env-step/window production) and version lag
    (born_version, the params version the data was generated under)."""

    __slots__ = ("trace_id", "born_ns", "born_version")

    def __init__(self, trace_id: str, born_ns: int, born_version: int):
        self.trace_id = trace_id
        self.born_ns = int(born_ns)
        self.born_version = int(born_version)

    def encode(self) -> str:
        """Wire form (the ``#t`` tag payload): three dot-separated hex
        fields — compact, and strictly validated on split so an agent id
        that happens to contain ``#t`` can never be misparsed."""
        return (f"{self.trace_id}.{self.born_ns:x}."
                f"{self.born_version & 0xFFFFFFFFFFFF:x}")

    _ID_CHARS = frozenset("0123456789abcdef-")

    @classmethod
    def decode(cls, text: str) -> "TrajCtx | None":
        parts = text.split(".")
        if len(parts) != 3 or not parts[0] \
                or not set(parts[0]) <= cls._ID_CHARS:
            return None
        try:
            return cls(parts[0], int(parts[1], 16), int(parts[2], 16))
        except ValueError:
            return None


def model_trace_id(version: int) -> str:
    return f"v{int(version)}"


class SpanRecorder:
    """Bounded in-memory flight recorder: the newest ``capacity`` spans,
    oldest evicted (a ring, not a leak — soak-length runs stay bounded
    no matter the sample rate)."""

    def __init__(self, capacity: int = 4096):
        self._spans: deque[dict] = deque(maxlen=max(16, int(capacity)))
        self._lock = threading.Lock()

    def record(self, span: dict) -> None:
        with self._lock:
            self._spans.append(span)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return list(self._spans)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


class Tracer:
    """The live tracing surface: sampling decisions, span recording,
    and the data-age/model-age histograms. One per process, installed by
    :func:`configure` (telemetry's ``configure_from_config`` does it when
    ``telemetry.trace_sample_rate > 0``)."""

    enabled = True

    def __init__(self, sample_rate: float, ring: int = 4096,
                 proc: str | None = None, journal: bool = True):
        from relayrl_tpu import telemetry
        from relayrl_tpu.telemetry.core import AGE_BUCKETS

        self.sample_rate = min(1.0, max(0.0, float(sample_rate)))
        self.recorder = SpanRecorder(ring)
        self.proc = proc or f"pid{os.getpid()}"
        # Wire-safe trace-id prefix: the ctx tag's validator admits
        # lowercase hex + '-' only (transport.base.split_agent_trace).
        self._id_prefix = f"{os.getpid():x}"
        self.journal = bool(journal)
        self._sample_lock = threading.Lock()
        self._accum = 0.0
        self._seq = 0
        reg = telemetry.get_registry()
        self._m_spans = reg.counter(
            "relayrl_trace_spans_total",
            "trace spans recorded into the flight recorder")
        self._m_sampled = reg.counter(
            "relayrl_trace_sampled_total",
            "trajectories that drew a trace context at emission")
        self._m_data_age = reg.histogram(
            "relayrl_trace_data_age_seconds",
            "end-to-end data age of sampled trajectories: env-step/window "
            "production to the update dispatch that consumed them "
            "(same-host monotonic pairs; skew-guarded)",
            buckets=AGE_BUCKETS)
        self._m_model_age = reg.histogram(
            "relayrl_trace_model_age_seconds",
            "model age at the actor: publish stamp to swap-applied "
            "(on_model return) for sampled versions; the analyzer adds "
            "the server-side dispatch→publish spans for the full "
            "dispatch→applied distribution",
            buckets=AGE_BUCKETS)
        self._m_data_lag = reg.histogram(
            "relayrl_trace_data_age_versions",
            "data age in model versions: consuming update's dispatched "
            "version minus the version the trajectory was generated "
            "under (the trace-context twin of "
            "relayrl_rlhf_train_lag_versions)",
            buckets=(0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0))

    # -- sampling --
    def _draw(self) -> int | None:
        """Stride sampling: deterministic, rate-exact over any window
        (every ceil(1/rate)-th draw fires) — reproducible in tests and
        cheap (one lock at trajectory granularity, never per step).
        Returns this draw's unique sequence number, or None. The seq is
        minted UNDER the lock — two threads that both fire must never
        share an id, or the analyzer would join their traces."""
        if self.sample_rate <= 0.0:
            return None
        with self._sample_lock:
            self._accum += self.sample_rate
            if self._accum >= 1.0:
                self._accum -= 1.0
                self._seq += 1
                return self._seq
            return None

    def sample_traj(self, born_ns: int, born_version: int) -> TrajCtx | None:
        """Per-trajectory sampling decision at emission time; the
        returned context rides the wire (``tag_agent_trace``)."""
        seq = self._draw()
        if seq is None:
            return None
        self._m_sampled.inc()
        return TrajCtx(f"{self._id_prefix}-{seq:x}", born_ns, born_version)

    def sample_id(self, kind: str) -> str | None:
        """Per-event sampling for self-contained planes (serving
        requests, RLHF stage rounds): a trace id, or None."""
        seq = self._draw()
        if seq is None:
            return None
        return f"{kind}-{self._id_prefix}-{seq:x}"

    def sample_version(self, version: int) -> bool:
        """Deterministic per-version sampling for the downstream model
        trace: every process running the same rate samples the SAME
        version set, so dispatch/publish/relay/receipt/swap hops record
        independently with no wire context. Version 0 (the handshake
        model) is never sampled."""
        rate = self.sample_rate
        if rate <= 0.0 or version <= 0:
            return False
        if rate >= 1.0:
            return True
        import hashlib

        digest = hashlib.blake2b(str(int(version)).encode(),
                                 digest_size=4).digest()
        return int.from_bytes(digest, "little") < int(rate * 2**32)

    # -- recording --
    def span(self, kind: str, trace_id: str, hop: str, t0_ns: int,
             t1_ns: int, **fields) -> None:
        rec = {"kind": kind, "trace": trace_id, "hop": hop,
               "proc": self.proc, "t0_ns": int(t0_ns), "t1_ns": int(t1_ns)}
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        self.recorder.record(rec)
        self._m_spans.inc()
        if self.journal:
            from relayrl_tpu import telemetry

            telemetry.emit("trace_span", **rec)

    def observe_data_age(self, age_s: float,
                         lag_versions: int | None = None) -> None:
        self._m_data_age.observe(age_s)
        if lag_versions is not None and lag_versions >= 0:
            self._m_data_lag.observe(float(lag_versions))

    def observe_model_age(self, age_s: float) -> None:
        self._m_model_age.observe(age_s)

    def snapshot(self) -> list[dict]:
        return self.recorder.snapshot()


class NullTracer:
    """Disabled mode: every surface is a no-op attribute call; sites
    gate their clock reads on ``.enabled`` so the hot paths stay
    untouched (asserted by benches/bench_telemetry.py)."""

    enabled = False
    sample_rate = 0.0
    proc = None

    def sample_traj(self, born_ns: int, born_version: int):
        return None

    def sample_id(self, kind: str):
        return None

    def sample_version(self, version: int) -> bool:
        return False

    def span(self, *args, **fields) -> None:
        pass

    def observe_data_age(self, age_s, lag_versions=None) -> None:
        pass

    def observe_model_age(self, age_s) -> None:
        pass

    def snapshot(self) -> list[dict]:
        return []


NULL_TRACER = NullTracer()
_tracer: Tracer | NullTracer = NULL_TRACER


def get_tracer() -> Tracer | NullTracer:
    """The process tracer (the shared :data:`NULL_TRACER` until
    configured). Instrumented sites call this per *trajectory/publish*,
    never per step."""
    return _tracer


def configure(sample_rate: float, ring: int = 4096,
              proc: str | None = None,
              journal: bool = True) -> Tracer | NullTracer:
    """Install the process tracer (idempotent against re-configure with
    rate 0 — a live tracer is never replaced by a null one so late
    config-bearing components can't disable an explicitly-enabled
    trace). Rate 0 leaves the null tracer in place."""
    global _tracer
    if float(sample_rate) <= 0.0:
        return _tracer
    _tracer = Tracer(sample_rate, ring=ring, proc=proc, journal=journal)
    return _tracer


def reset_for_tests() -> None:
    global _tracer
    _tracer = NULL_TRACER


def snapshot_spans() -> list[dict]:
    """The live flight-recorder ring (drills, tests, embedders)."""
    return _tracer.snapshot()


def traces_document() -> dict:
    """The ``/traces`` endpoint body: the live flight-recorder ring."""
    tr = _tracer
    return {
        "schema": "relayrl-trace-v1",
        "enabled": tr.enabled,
        "proc": tr.proc,
        "sample_rate": getattr(tr, "sample_rate", 0.0),
        "spans": tr.snapshot(),
    }


def record_model_receipt(version: int, rx_ns: int, pub_ns: int | None,
                         backend: str) -> None:
    """Shared actor-transport hook, called right after ``on_model``
    returns (zmq/grpc/native deliver sites): records the ``receipt``
    hop span for sampled versions (receipt stamp → swap-applied) and
    observes model age when the frame carried the publisher's monotonic
    stamp — same skew guard as the receipt-latency histogram."""
    tr = _tracer
    if not tr.enabled:
        return
    done = time.monotonic_ns()
    if tr.sample_version(version):
        tr.span("model", model_trace_id(version), "receipt", rx_ns, done,
                backend=backend, version=int(version))
    if pub_ns is not None and 0 <= done - pub_ns < SKEW_GUARD_NS:
        tr.observe_model_age((done - pub_ns) / 1e9)


def split_ctx(agent_id: str) -> tuple[str, TrajCtx | None]:
    """Strip + decode a ``#t`` trace tag from an (already seq-stripped)
    envelope id. Unconditional on the server ingest path — like the seq
    tag, the trace tag must never leak into attribution even when this
    process traces nothing."""
    base, text = split_agent_trace(agent_id)
    if text is None:
        return agent_id, None
    ctx = TrajCtx.decode(text)
    return (base, ctx) if ctx is not None else (agent_id, None)


# -- Chrome-trace export ----------------------------------------------------

_CORE_KEYS = ("kind", "trace", "hop", "proc", "t0_ns", "t1_ns")


def to_chrome_trace(spans: list[dict]) -> dict:
    """Spans → Chrome Trace Event JSON (``chrome://tracing`` /
    Perfetto): complete ("X") events, microsecond timestamps, one pid
    row per process, one tid row per trace."""
    events = []
    for s in spans:
        t0 = int(s.get("t0_ns", 0))
        t1 = max(t0, int(s.get("t1_ns", t0)))
        events.append({
            "name": s.get("hop", "?"),
            "cat": s.get("kind", "?"),
            "ph": "X",
            "ts": t0 / 1e3,
            "dur": max(0.001, (t1 - t0) / 1e3),
            "pid": s.get("proc", "?"),
            "tid": s.get("trace", "?"),
            "args": {k: v for k, v in s.items() if k not in _CORE_KEYS},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- critical-path analyzer -------------------------------------------------

def spans_from_events(events: list[dict]) -> list[dict]:
    """``trace_span`` journal records → span dicts (the journal adds
    run_id/t_unix/mono_ns around the span fields; strip the envelope)."""
    out = []
    for e in events:
        if e.get("event") != "trace_span":
            continue
        span = {k: v for k, v in e.items()
                if k not in ("event", "run_id", "t_unix", "mono_ns")}
        if "t0_ns" in span and "t1_ns" in span:
            out.append(span)
    return out


def load_spans(paths: list[str] = (), urls: list[str] = ()) -> list[dict]:
    """Gather spans from NDJSON journals (``trace_span`` events) and/or
    live ``/traces`` endpoints, deduplicated (a span may sit in both the
    ring and the journal)."""
    from relayrl_tpu.telemetry.events import read_events

    spans: list[dict] = []
    for path in paths:
        spans.extend(spans_from_events(read_events(path)))
    for url in urls:
        import urllib.request

        with urllib.request.urlopen(url.rstrip("/") + "/traces",
                                    timeout=10.0) as resp:
            doc = json.loads(resp.read().decode())
        spans.extend(doc.get("spans", []))
    seen = set()
    unique = []
    for s in spans:
        key = (s.get("kind"), s.get("trace"), s.get("hop"),
               s.get("proc"), s.get("t0_ns"),
               s.get("actor") or s.get("agent") or s.get("backend"))
        if key in seen:
            continue
        seen.add(key)
        unique.append(s)
    return unique


def _dist(values: list[float]) -> dict:
    if not values:
        return {"count": 0}
    vs = sorted(values)

    def pct(q: float) -> float:
        return vs[min(len(vs) - 1, int(q * len(vs)))]

    return {"count": len(vs), "mean": sum(vs) / len(vs),
            "p50": pct(0.5), "p95": pct(0.95), "max": vs[-1]}


def _sort_hops(spans: list[dict], order: dict) -> list[dict]:
    return sorted(spans, key=lambda s: (order.get(s["hop"], 99),
                                        s["t0_ns"]))


def analyze(spans: list[dict]) -> dict:
    """Reduce spans to critical-path attribution.

    * per-hop latency: total/mean/p95 span duration by (kind, hop);
    * trajectory traces: completeness (saw env AND update), data-age
      seconds + version lag per complete trace, inter-hop gap share;
    * model traces: model age (dispatch t0 → each swap t1) per
      (version, actor) pair — one version swapping on N actors yields N
      ages — plus distinct-actor and relay-hop counts.

    Cross-process joins apply the same-host skew guard: a negative or
    >300 s delta is dropped as clock skew, counted in ``skew_dropped``.
    """
    by_hop: dict[tuple, list[float]] = {}
    traj: dict[str, list[dict]] = {}
    model: dict[str, list[dict]] = {}
    for s in spans:
        kind = s.get("kind")
        dur = max(0, int(s["t1_ns"]) - int(s["t0_ns"])) / 1e9
        by_hop.setdefault((kind, s["hop"]), []).append(dur)
        if kind == "traj":
            traj.setdefault(s["trace"], []).append(s)
        elif kind == "model":
            model.setdefault(s["trace"], []).append(s)

    data_ages, data_lags = [], []
    gaps = []
    skew_dropped = 0
    complete_traj = 0
    for tid, ss in traj.items():
        hops = _sort_hops(ss, _HOP_ORDER)
        env = next((h for h in hops if h["hop"] == "env"), None)
        upd = next((h for h in reversed(hops) if h["hop"] == "update"),
                   None)
        if env is None or upd is None:
            continue
        age_ns = int(upd["t1_ns"]) - int(env["t0_ns"])
        if not (0 <= age_ns < SKEW_GUARD_NS):
            skew_dropped += 1
            continue
        complete_traj += 1
        data_ages.append(age_ns / 1e9)
        if "version" in upd and "version" in env:
            data_lags.append(max(0, int(upd["version"])
                                 - int(env["version"])))
        span_total = sum(max(0, h["t1_ns"] - h["t0_ns"]) for h in hops)
        gaps.append(max(0.0, (age_ns - span_total) / 1e9))

    model_ages = []
    model_traces = {}
    for tid, ss in model.items():
        hops = _sort_hops(ss, _MODEL_ORDER)
        disp = next((h for h in hops if h["hop"] == "dispatch"), None)
        swaps = [h for h in hops if h["hop"] == "swap"]
        relays = [h for h in hops if h["hop"] == "relay"]
        entry = {"hops": sorted({h["hop"] for h in hops},
                                key=lambda h: _MODEL_ORDER.get(h, 99)),
                 "actors": sorted({h.get("actor", h.get("proc", "?"))
                                   for h in swaps}),
                 "relay_hops": len(relays)}
        model_traces[tid] = entry
        if disp is None:
            continue
        for sw in swaps:
            age_ns = int(sw["t1_ns"]) - int(disp["t0_ns"])
            if 0 <= age_ns < SKEW_GUARD_NS:
                model_ages.append(age_ns / 1e9)
            else:
                skew_dropped += 1

    return {
        "spans": len(spans),
        "per_hop": {
            f"{kind}:{hop}": _dist(vals)
            for (kind, hop), vals in sorted(by_hop.items())
        },
        "trajectories": {
            "traced": len(traj),
            "complete": complete_traj,
            "data_age_s": _dist(data_ages),
            "data_age_versions": _dist([float(v) for v in data_lags]),
            "inter_hop_gap_s": _dist(gaps),
        },
        "models": {
            "traced": len(model),
            "model_age_s": _dist(model_ages),
            "traces": model_traces,
        },
        "skew_dropped": skew_dropped,
    }


def render_report(report: dict) -> str:
    """Analyzer report → operator text (the CLI's default output)."""
    lines = [f"trace analysis · {report['spans']} spans"]
    lines.append("-- per-hop latency "
                 + "-" * 41)
    for key, dist in report["per_hop"].items():
        if not dist["count"]:
            continue
        lines.append(
            f"  {key:<18} n={dist['count']:<6} "
            f"mean={dist['mean'] * 1e3:8.3f}ms "
            f"p95={dist['p95'] * 1e3:8.3f}ms")
    tj = report["trajectories"]
    lines.append(f"-- trajectories: {tj['traced']} traced, "
                 f"{tj['complete']} complete "
                 + "-" * 20)
    for label, key in (("data age", "data_age_s"),
                       ("inter-hop gap", "inter_hop_gap_s")):
        d = tj[key]
        if d["count"]:
            lines.append(
                f"  {label:<14} n={d['count']:<6} "
                f"mean={d['mean'] * 1e3:8.3f}ms "
                f"p50={d['p50'] * 1e3:8.3f}ms "
                f"p95={d['p95'] * 1e3:8.3f}ms")
    d = tj["data_age_versions"]
    if d["count"]:
        lines.append(f"  version lag    n={d['count']:<6} "
                     f"mean={d['mean']:.2f} p95={d['p95']:.0f}")
    mo = report["models"]
    lines.append(f"-- model versions: {mo['traced']} traced "
                 + "-" * 28)
    d = mo["model_age_s"]
    if d["count"]:
        lines.append(
            f"  model age      n={d['count']:<6} "
            f"mean={d['mean'] * 1e3:8.3f}ms "
            f"p50={d['p50'] * 1e3:8.3f}ms "
            f"p95={d['p95'] * 1e3:8.3f}ms")
    if report["skew_dropped"]:
        lines.append(f"  skew-dropped pairs: {report['skew_dropped']}")
    return "\n".join(lines) + "\n"


def main(argv: list[str] | None = None) -> int:
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m relayrl_tpu.telemetry.trace",
        description="critical-path analyzer over relayrl trace spans "
                    "(NDJSON journals and/or live /traces endpoints)")
    parser.add_argument("journals", nargs="*",
                        help="event-journal NDJSON files carrying "
                             "trace_span events")
    parser.add_argument("--url", action="append", default=[],
                        help="telemetry exporter base URL; its /traces "
                             "ring joins the analysis (repeatable)")
    parser.add_argument("--json", action="store_true",
                        help="emit the full report as JSON")
    parser.add_argument("--chrome", metavar="OUT",
                        help="also write a Chrome-trace JSON "
                             "(chrome://tracing / Perfetto)")
    args = parser.parse_args(argv)
    if not args.journals and not args.url:
        parser.error("need at least one journal file or --url")
    spans = load_spans(args.journals, args.url)
    report = analyze(spans)
    if args.chrome:
        with open(args.chrome, "w") as f:
            json.dump(to_chrome_trace(spans), f)
        print(f"chrome trace written to {args.chrome} "
              f"({len(spans)} spans)", file=sys.stderr)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render_report(report), end="")
    return 0


__all__ = [
    "TRAJ_HOPS", "MODEL_HOPS", "SERVE_HOPS", "RLHF_HOPS",
    "SKEW_GUARD_NS", "TrajCtx", "SpanRecorder", "Tracer", "NullTracer",
    "NULL_TRACER", "get_tracer", "configure", "reset_for_tests",
    "traces_document", "snapshot_spans", "model_trace_id",
    "record_model_receipt",
    "split_ctx", "tag_agent_trace", "split_agent_trace",
    "to_chrome_trace", "spans_from_events", "load_spans", "analyze",
    "render_report", "main",
]


if __name__ == "__main__":
    import sys

    sys.exit(main())
