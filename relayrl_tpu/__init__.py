"""relayrl_tpu — a TPU-native distributed actor↔learner RL framework.

A from-scratch re-design of the capabilities of `jrcalgo/RelayRL-prototype`
(see SURVEY.md): actor processes run environment steps against a locally-held
policy and stream trajectories over ZMQ/gRPC/native transports to a training
server whose learner is a pure JAX/XLA program (jit/pjit policy-gradient
updates over a device mesh), publishing updated parameters back to actors for
hot-swap.

Public API mirrors the reference's five PyO3 classes
(reference: relayrl_framework/src/lib.rs:163-186) in TPU-native form:

- :class:`relayrl_tpu.types.ActionRecord`   (ref: RelayRLAction)
- :class:`relayrl_tpu.types.Trajectory`     (ref: RelayRLTrajectory)
- :class:`relayrl_tpu.config.ConfigLoader`  (ref: ConfigLoader)
- :class:`relayrl_tpu.runtime.TrainingServer` (ref: TrainingServer)
- :class:`relayrl_tpu.runtime.Agent`        (ref: RelayRLAgent)
"""

__version__ = "0.1.0"

from relayrl_tpu.types import ActionRecord, Trajectory, TensorSpec, DType  # noqa: F401
from relayrl_tpu.config import ConfigLoader  # noqa: F401

__all__ = [
    "ActionRecord",
    "Trajectory",
    "TensorSpec",
    "DType",
    "ConfigLoader",
    "__version__",
]


def __getattr__(name):
    # Lazy imports for heavyweight submodules so `import relayrl_tpu` stays
    # cheap in actor processes that only need types + config.
    if name in ("TrainingServer", "Agent", "LocalRunner",
                "ApplicationAbstract", "VectorAgent", "VectorActorHost"):
        from relayrl_tpu import runtime

        return getattr(runtime, name)
    raise AttributeError(f"module 'relayrl_tpu' has no attribute {name!r}")
