"""Container package for the bundled native library.

Wheel builds place ``librelayrl_native.so`` here (see setup.py); source
checkouts use ``native/librelayrl_native.so`` built by ``make -C
native``. ``transport.native_backend._find_library`` checks both."""

import os


def bundled_library_path() -> str | None:
    """Path of the wheel-bundled .so, or None in a source checkout."""
    cand = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "librelayrl_native.so")
    return cand if os.path.isfile(cand) else None
